"""Binary Merkle commitment: fixed-shape 2-ary keccak nodes, bit paths.

The scheme three of the five PAPERS.md papers point at (2504.14069:
binary Merkle dominates hexary MPT on witness bytes; 2606.11736 MHOT:
height-optimized layouts with path compression beat the canonical trie
on proof depth): a Patricia tree over the 256 BITS of the keccak'd key,
with MHOT-style path compression (extension levels carry skipped bit
runs, leaves carry their remaining bit suffix) and every child
referenced by its 32-byte keccak digest — no <32 B embedding, so every
node is a fixed-shape hashing unit.

Node encodings (THE REF-TRANSPARENCY CONTRACT, phant_tpu/commitment/
__init__.py): each node is a single RLP list whose child refs sit
exactly where the shared ref scanners already look, so binary witnesses
flow through all three witness-engine cores, the fused device kernel
and the device-resident table with zero scanner changes:

  * internal (2-ary branch): a 17-item list `[left, right, "" x 15]`
    with both children as 32-byte digests — semantically strictly
    2-ary (slots 2..15 and the value slot are ALWAYS empty; the codec
    rejects anything else), framed so the scanners' branch rule
    extracts both child refs. 83 bytes fixed — one keccak rate chunk,
    vs up to 563 B for a dense hexary branch; the ~19-byte framing tax
    over a raw 64-byte `left||right` payload buys the entire existing
    verification stack unmodified (documented in README);
  * extension: `[bit_prefix(path, leaf=0), child_digest]` — the pair
    rule (0x20 bit clear) extracts the child ref;
  * leaf: `[bit_prefix(path, leaf=1), value]` — account-shaped values
    expose their storage root through the scanners' account-leaf rule,
    exactly like the hexary account leaf (the account VALUE encoding is
    scheme-independent, see CommitmentScheme).

Bit-prefix path encoding (the hex-prefix analogue for bit strings):
2 header bytes + ceil(nbits/8) big-endian bit bytes. Header byte 0 =
0x20*is_leaf | high bit of the 9-bit count (0..256), byte 1 = count's
low 8 bits; trailing pad bits must be zero (canonical encodings only).
The 0x20 flag deliberately lands on the same bit the hex-prefix leaf
flag uses — that is what the shared pair-node scanner rule keys on.

Hash-plan lowering: `BinaryPlanBuilder` is the stock PlanBuilder with
the bit-prefix path encoder and the embedded-node rule disabled (binary
always refs by digest, so every subtree is plannable) — HashPlan,
merge_plans, RootEngine and the scheduler's root lane are template-
agnostic and run binary plans unchanged.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from phant_tpu import rlp
from phant_tpu.commitment import CommitmentScheme, register_scheme
from phant_tpu.crypto.keccak import keccak256
from phant_tpu.mpt.mpt import (
    EMPTY_TRIE_ROOT,
    BranchNode,
    ExtensionNode,
    LeafNode,
    Trie,
)

# module-level on purpose (no cycle: stateless.py reaches commitment/ only
# lazily at call time, never at import time) and jax-free — the binary
# scheme must stay importable on the pure-CPU serving path; the one
# jax-adjacent piece (the plan builder over ops/mpt_jax) is lazy below
from phant_tpu.stateless import HashNode, PartialTrie, StatelessError

#: per-byte bit tuples (MSB first) — key digitization is on the state
#: materialization path, so it's a table lookup, not per-bit arithmetic
_BIT_TABLE = tuple(
    tuple((b >> i) & 1 for i in range(7, -1, -1)) for b in range(256)
)


def bytes_to_bits(key: bytes) -> Tuple[int, ...]:
    """MSB-first bit digits of `key` (bit i of byte b is digit 8*b+7-i)."""
    return tuple(bit for byte in key for bit in _BIT_TABLE[byte])


def encode_bit_prefix(bits, is_leaf: bool) -> bytes:
    """Bit-string path encoding: [flags|count_hi, count_lo, bit bytes...].
    The 0x20 leaf flag intentionally matches hex-prefix so the shared
    pair-node ref-scanner rule (leaf vs extension) applies unchanged."""
    n = len(bits)
    if n > 256:
        raise ValueError(f"bit path of {n} digits exceeds the 256-bit key")
    out = bytearray(2 + (n + 7) // 8)
    out[0] = (0x20 if is_leaf else 0x00) | ((n >> 8) & 0x01)
    out[1] = n & 0xFF
    for i, bit in enumerate(bits):
        if bit:
            out[2 + (i >> 3)] |= 0x80 >> (i & 7)
    return bytes(out)


def decode_bit_prefix(data: bytes) -> Tuple[Tuple[int, ...], bool]:
    """Strict inverse of `encode_bit_prefix`: unknown flag bits, length
    mismatches and nonzero pad bits are all rejected (non-canonical path
    encodings must not alias distinct committed trees)."""
    if len(data) < 2:
        raise ValueError("bit-prefix path too short")
    flag = data[0]
    if flag & ~0x21:
        raise ValueError("bad bit-prefix flag byte")
    is_leaf = bool(flag & 0x20)
    n = ((flag & 0x01) << 8) | data[1]
    if n > 256:
        raise ValueError(f"bit path of {n} digits exceeds the 256-bit key")
    nbytes = (n + 7) // 8
    if len(data) != 2 + nbytes:
        raise ValueError("bit-prefix length mismatch")
    if n & 7:
        pad_mask = (1 << (8 - (n & 7))) - 1
        if data[-1] & pad_mask:
            raise ValueError("nonzero bit-prefix pad bits")
    bits = tuple(
        (data[2 + (i >> 3)] >> (7 - (i & 7))) & 1 for i in range(n)
    )
    return bits, is_leaf


# ---------------------------------------------------------------------------
# tries
# ---------------------------------------------------------------------------


class BinaryTrie(Trie):
    """A build-once/query binary Patricia tree over byte keys.

    Reuses mpt.py's radix-generic structure algorithms wholesale: the
    digit alphabet is {0, 1} (so only `children[0]`/`children[1]` of the
    stock 16-slot BranchNode are ever populated), paths encode with the
    bit-prefix codec, and `_ref` ALWAYS hashes — the fixed-shape rule
    that makes every node a digest-referenced unit."""

    _digits = staticmethod(bytes_to_bits)
    _path_enc = staticmethod(encode_bit_prefix)

    def _ref(self, node) -> bytes:
        # no embedding: children are referenced by digest regardless of
        # encoding size (fixed-shape 2-ary rule)
        return keccak256(self.node_encoding(node)[1])


def _resolve_binary(digest: bytes, db: Dict[bytes, bytes]):
    enc = db.get(digest)
    if enc is None:
        return HashNode(digest)
    return decode_binary_node(rlp.decode(enc), db)


def decode_binary_node(item: rlp.RLPItem, db: Dict[bytes, bytes]):
    """Decoded binary witness structure -> node graph (HashNode at the
    witness edges). STRICTLY 2-ary: a 17-item frame with anything in
    slots 2..16, a missing branch child, an embedded (list-valued) child
    or a non-canonical bit prefix is rejected — the frame is for ref-
    scanner transparency, not for smuggling hexary structure."""
    if not isinstance(item, list):
        raise StatelessError("binary trie node is not an RLP list")
    if len(item) == 17:
        branch = BranchNode()
        for i in (0, 1):
            child = item[i]
            if isinstance(child, list) or len(child) != 32:
                raise StatelessError(
                    "binary branch child must be a 32-byte digest"
                )
            branch.children[i] = _resolve_binary(bytes(child), db)
        for i in range(2, 16):
            if isinstance(item[i], list) or len(item[i]) != 0:
                raise StatelessError("binary branch with >2 children")
        if isinstance(item[16], list) or len(item[16]) != 0:
            raise StatelessError("binary branch must not carry a value")
        return branch
    if len(item) == 2:
        if isinstance(item[0], list):
            raise StatelessError("bad binary path item")
        try:
            path, is_leaf = decode_bit_prefix(bytes(item[0]))
        except ValueError as e:
            raise StatelessError(f"bad bit-prefix path: {e}") from None
        if is_leaf:
            if isinstance(item[1], list) or len(item[1]) == 0:
                raise StatelessError("bad binary leaf value")
            return LeafNode(path, bytes(item[1]))
        if not path:
            raise StatelessError("binary extension with empty path")
        child = item[1]
        if isinstance(child, list) or len(child) != 32:
            raise StatelessError(
                "binary extension child must be a 32-byte digest"
            )
        return ExtensionNode(path, _resolve_binary(bytes(child), db))
    raise StatelessError(f"binary trie node with {len(item)} items")


class PartialBinaryTrie(PartialTrie, BinaryTrie):
    """A witness-backed binary partial tree (the PartialTrie analogue).

    Pure hook composition, no method bodies: PartialTrie supplies the
    witness semantics (HashNode edges and their `_ref` digest
    passthrough, insufficient-witness errors, deletion poisoning) — all
    radix-generic — BinaryTrie supplies the codec (`_digits`,
    `_path_enc`, always-hash `_ref` via the MRO), and the one
    scheme-specific piece is the witness decoder hook."""

    _resolve_witness = staticmethod(_resolve_binary)


# ---------------------------------------------------------------------------
# hash-plan lowering (the batched root lane)
# ---------------------------------------------------------------------------


import functools as _functools


@_functools.lru_cache(maxsize=1)
def _binary_plan_builder_cls():
    """The BinaryPlanBuilder class, built ONCE on first use — the import
    of ops/mpt_jax (which pulls in jax) is what must stay lazy, not the
    class statement: plan_builder() runs per request on the serving
    post-root path."""
    from phant_tpu.ops.mpt_jax import PlanBuilder

    class BinaryPlanBuilder(PlanBuilder):
        _path_enc = staticmethod(encode_bit_prefix)
        _min_template = 0

    return BinaryPlanBuilder


def binary_plan_builder():
    """The stock level-template planner with the binary codec: bit-prefix
    paths, and `_min_template = 0` because binary NEVER embeds — every
    subtree is plannable, so the only host-walk fallback left is the
    oversized-node guard. HashPlan / merge_plans / RootEngine and the
    scheduler's root lane consume the result unchanged (templates with
    32-byte holes are scheme-agnostic)."""
    return _binary_plan_builder_cls()()


# ---------------------------------------------------------------------------
# the scheme
# ---------------------------------------------------------------------------


class BinaryScheme(CommitmentScheme):
    name = "binary"
    #: keccak(rlp(b"")) — the empty-tree root is shared with the hexary
    #: scheme by design: `verify_witness_nodes`' empty-pre-state contract
    #: and the storage-root sentinels stay scheme-independent
    empty_root = EMPTY_TRIE_ROOT

    def fresh_trie(self) -> BinaryTrie:
        return BinaryTrie()

    def partial_trie(self, root_digest: bytes, db: Dict[bytes, bytes]):
        return PartialBinaryTrie(root_digest, db)

    def plan_builder(self):
        return binary_plan_builder()

    # -- witnesses -----------------------------------------------------------

    def collect_nodes(self, trie: Trie, nodes: Dict[bytes, None]) -> None:
        """The binary witness pack loop: EVERY node encoding ships (all
        children are digest-referenced, so all nodes are witness units).
        Serving-hot (witness generation for the differential/bench
        spans) — phantlint HOSTSYNC watches it."""
        if trie.root is None:
            return
        stack = [trie.root]
        while stack:
            node = stack.pop()
            nodes[trie.node_encoding(node)[1]] = None
            if isinstance(node, ExtensionNode):
                stack.append(node.child)
            elif isinstance(node, BranchNode):
                for child in node.children:
                    if child is not None:
                        stack.append(child)

    def proof_nodes(self, trie: Trie, key: bytes) -> List[bytes]:
        """Node encodings along `key`'s lookup path (presence or
        witnessed absence) — sibling digests ride inside the 2-ary
        parents, so the path nodes alone are the proof."""
        out: List[bytes] = []
        node, path = trie.root, list(bytes_to_bits(key))
        while node is not None:
            out.append(trie.node_encoding(node)[1])
            if isinstance(node, LeafNode):
                break
            if isinstance(node, ExtensionNode):
                n = len(node.path)
                if tuple(path[:n]) != node.path:
                    break
                node, path = node.child, path[n:]
                continue
            if not path:
                break
            node, path = node.children[path[0]], path[1:]
        return out


register_scheme(BinaryScheme())
