"""Pluggable commitment schemes: how world state is committed.

The verification stack was built around ONE commitment scheme — the
hexary keccak Merkle Patricia Trie — but nothing in its hot layers
actually depends on hexary RLP semantics:

  * the witness engine (ops/witness_engine.py, all three cores), the
    fused device kernel (ops/witness_jax.py) and the device-resident
    intern table (ops/witness_resident.py) verify "these nodes form a
    connected subtree rooted at the claimed digest" over any node bytes
    whose child references the ref scanners can see;
  * the hash-plan executors (ops/mpt_jax.py HashPlan / merge_plans,
    ops/root_engine.py, the scheduler's root lane) hash "templates with
    32-byte holes at byte offsets" — they never look inside a template;
  * the trie STRUCTURE algorithms (mpt/mpt.py insert/delete/collapse)
    are radix-generic over `children[digit]`.

This package makes that seam explicit. A `CommitmentScheme` bundles the
scheme-specific pieces — key digitization, node codec, partial-trie
construction from a witness, hash-plan lowering, witness generation —
behind one object, and everything scheme-dependent in stateless.py /
spec/runner.py / bench resolves through it. Two backends ship:

  * `mpt` (commitment/mpt_scheme.py): the paper's hexary keccak MPT,
    byte-identical to the pre-plugin code path (the default);
  * `binary` (commitment/binary.py): fixed-shape 2-ary keccak Merkle
    nodes with bit-level path compression a la MHOT (PAPERS.md
    2606.11736) — the scheme three of the five related papers argue is
    the stateless endgame (2504.14069: binary dominates hexary on
    witness bytes).

THE REF-TRANSPARENCY CONTRACT (what lets a new scheme ride the whole
existing stack unmodified): a scheme's node encoding must be a single
RLP list in which every child reference appears where the shared ref
scanners (_scan_list_refs / native packer.cc / the device
_extract_ref_positions — all differential-tested identical) already
look: 32-byte string children of a 17-item list, the 32-byte second
item of a 2-item list whose first item's 0x20 bit is clear, or the
storage root inside an account-shaped leaf value. Schemes that speak
this contract get all three engine cores, the fused kernel, the
resident table, the serving scheduler and the mesh lanes for free;
a scheme that cannot (e.g. a non-keccak Verkle commitment) plugs in
below the same interface but must bring its own verifier route.

Selection: `PHANT_COMMITMENT` (the `--commitment={mpt,binary}` CLI
flag sets it) picks the process-wide active scheme; library callers can
pass an explicit scheme to `WitnessStateDB` / `execute_stateless`.
"""

from __future__ import annotations

import os
from typing import Dict, List, Mapping, Tuple


def account_leaf_value(
    nonce: int, balance: int, storage_root: bytes, code_hash: bytes
) -> bytes:
    """THE account leaf VALUE encoding — rlp([nonce, balance,
    storage_root, code_hash]). One copy, shared by every scheme
    (CommitmentScheme.account_leaf), the hexary state builders
    (state/root.py) and the stateless write-back path
    (stateless.WitnessStateDB): the value encoding is the state MODEL,
    and a divergence between the producers would be a silent root
    split."""
    from phant_tpu import rlp

    return rlp.encode(
        [rlp.encode_uint(nonce), rlp.encode_uint(balance), storage_root, code_hash]
    )


class CommitmentScheme:
    """One way of committing world state to a 32-byte root.

    Subclasses supply trie construction (full and witness-backed),
    hash-plan lowering, and witness generation. The account/storage KEY
    derivation (keccak(address) / keccak(slot_be32)) and the account
    leaf VALUE encoding (rlp([nonce, balance, storage_root, code_hash]))
    are deliberately shared across schemes — they are part of the state
    MODEL, not of how the tree commits to it — which is also what makes
    the account-leaf storage-root ref visible to the shared scanners.
    """

    #: registry key and the `--commitment` flag value
    name: str = "abstract"
    #: root of the empty trie (keccak(rlp(b"")) for both keccak schemes)
    empty_root: bytes = b""

    # -- tries ---------------------------------------------------------------

    def fresh_trie(self):
        """An empty buildable trie of this scheme."""
        raise NotImplementedError

    def partial_trie(self, root_digest: bytes, db: Dict[bytes, bytes]):
        """A witness-backed partial trie (unwitnessed subtrees opaque);
        raises StatelessError when the witness misses the root."""
        raise NotImplementedError

    def plan_builder(self):
        """A PlanBuilder lowering this scheme's dirty nodes into a
        HashPlan (ops/mpt_jax.py) for the batched root lane."""
        raise NotImplementedError

    # -- state commitment ----------------------------------------------------

    def build_storage_trie(self, storage: Mapping[int, int]):
        trie = self.fresh_trie()
        from phant_tpu.crypto.keccak import keccak256
        from phant_tpu import rlp

        for slot, value in storage.items():
            if value == 0:
                continue
            trie.put(
                keccak256(slot.to_bytes(32, "big")),
                rlp.encode(rlp.encode_uint(value)),
            )
        return trie

    def account_leaf(self, account) -> bytes:
        return account_leaf_value(
            account.nonce,
            account.balance,
            self.build_storage_trie(account.storage).root_hash(),
            account.code_hash(),
        )

    def build_state_trie(self, accounts: Mapping[bytes, object]):
        """address -> account trie, skipping EIP-161-empty accounts
        (same account-model semantics for every scheme)."""
        from phant_tpu.crypto.keccak import keccak256

        trie = self.fresh_trie()
        for address, account in accounts.items():
            if account.is_empty() and not account.storage:
                continue
            trie.put(keccak256(address), self.account_leaf(account))
        return trie

    def state_root_of(self, accounts: Mapping[bytes, object]) -> bytes:
        return self.build_state_trie(accounts).root_hash()

    # -- witnesses -----------------------------------------------------------

    def collect_nodes(self, trie, nodes: Dict[bytes, None]) -> None:
        """Add every witness-shippable node encoding of `trie` to `nodes`
        (an ordered set). Scheme-specific: the hexary scheme skips
        embedded (<32 B) nodes, the binary scheme ships every node."""
        raise NotImplementedError

    def proof_nodes(self, trie, key: bytes) -> List[bytes]:
        """The witness nodes proving `key`'s presence/absence: the node
        encodings along the lookup path (sibling digests are embedded in
        the path nodes themselves for both keccak schemes)."""
        raise NotImplementedError

    def witness_of_state(self, accounts: Mapping[bytes, object]) -> Tuple[
        bytes, List[bytes], List[bytes]
    ]:
        """(state_root, nodes, codes): the FULL state (accounts + storage
        subtrees) as a witness — the provably-sufficient witness the spec
        runner executes against (phant_tpu/spec/runner.py)."""
        from phant_tpu.utils.trace import metrics

        nodes: Dict[bytes, None] = {}
        codes: Dict[bytes, None] = {}
        for acct in accounts.values():
            if acct.code:
                codes[acct.code] = None
            if any(v for v in acct.storage.values()):
                self.collect_nodes(self.build_storage_trie(acct.storage), nodes)
        trie = self.build_state_trie(accounts)
        self.collect_nodes(trie, nodes)
        metrics.count(
            "commitment.witness_nodes", len(nodes), scheme=self.name
        )
        return trie.root_hash(), list(nodes), list(codes)


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

_SCHEMES: Dict[str, CommitmentScheme] = {}


def register_scheme(scheme: CommitmentScheme) -> CommitmentScheme:
    _SCHEMES[scheme.name] = scheme
    return scheme


def scheme_names() -> Tuple[str, ...]:
    _load_builtin()
    return tuple(sorted(_SCHEMES))


def get_scheme(name: str) -> CommitmentScheme:
    _load_builtin()
    try:
        return _SCHEMES[name]
    except KeyError:
        raise ValueError(
            f"unknown commitment scheme {name!r} (have: {sorted(_SCHEMES)})"
        ) from None


def active_scheme() -> CommitmentScheme:
    """The process-wide scheme: PHANT_COMMITMENT (default `mpt` — the
    paper's hexary keccak MPT, byte-identical to the pre-plugin path).
    Read per call so tests/CLI can flip it without import-order games;
    the env read is a dict lookup, nowhere near any hot loop (states are
    constructed once per request)."""
    return get_scheme(os.environ.get("PHANT_COMMITMENT", "mpt") or "mpt")


def _load_builtin() -> None:
    if "mpt" not in _SCHEMES:
        from phant_tpu.commitment import mpt_scheme  # noqa: F401  (registers)
    if "binary" not in _SCHEMES:
        from phant_tpu.commitment import binary  # noqa: F401  (registers)
