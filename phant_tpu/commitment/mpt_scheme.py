"""The default commitment scheme: the paper's hexary keccak MPT.

Pure delegation to the pre-plugin machinery — mpt/mpt.py tries,
stateless.PartialTrie, ops/mpt_jax.PlanBuilder, state/root.py builders,
spec-runner witness collection — so the default path stays byte-identical
to the code before the commitment/ refactor (pinned by every existing
suite running unmodified)."""

from __future__ import annotations

from typing import Dict, List, Mapping

from phant_tpu.commitment import CommitmentScheme, register_scheme
from phant_tpu.mpt.mpt import EMPTY_TRIE_ROOT, BranchNode, ExtensionNode, Trie


class MptScheme(CommitmentScheme):
    name = "mpt"
    empty_root = EMPTY_TRIE_ROOT

    def fresh_trie(self) -> Trie:
        return Trie()

    def partial_trie(self, root_digest: bytes, db: Dict[bytes, bytes]):
        from phant_tpu.stateless import PartialTrie

        return PartialTrie(root_digest, db)

    def plan_builder(self):
        from phant_tpu.ops.mpt_jax import PlanBuilder

        return PlanBuilder()

    # -- state commitment: the state/root.py builders verbatim --------------

    def build_storage_trie(self, storage: Mapping[int, int]) -> Trie:
        from phant_tpu.state.root import build_storage_trie

        return build_storage_trie(storage)

    def account_leaf(self, account) -> bytes:
        from phant_tpu.state.root import account_leaf

        return account_leaf(account)

    def build_state_trie(self, accounts) -> Trie:
        from phant_tpu.state.root import build_state_trie

        return build_state_trie(accounts)

    def state_root_of(self, accounts) -> bytes:
        from phant_tpu.state.root import state_root

        return state_root(accounts)

    # -- witnesses -----------------------------------------------------------

    def collect_nodes(self, trie: Trie, nodes: Dict[bytes, None]) -> None:
        """Every >=32 B node encoding (embedded nodes travel inside their
        parents; the root ships regardless) — exactly the spec runner's
        pre-plugin collection."""
        if trie.root is None:
            return

        def walk(node):
            _s, enc = trie.node_encoding(node)
            if len(enc) >= 32 or node is trie.root:
                nodes[enc] = None
            if isinstance(node, ExtensionNode):
                walk(node.child)
            elif isinstance(node, BranchNode):
                for child in node.children:
                    if child is not None:
                        walk(child)

        walk(trie.root)

    def proof_nodes(self, trie: Trie, key: bytes) -> List[bytes]:
        from phant_tpu.mpt.proof import generate_proof

        return generate_proof(trie, key)


register_scheme(MptScheme())
