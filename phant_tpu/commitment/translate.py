"""Fixture translation: re-commit spec-test fixtures under another scheme.

The execution-spec-tests fixtures commit state with the hexary MPT: every
block header's `state_root` (and, downstream of header hashes, every
`parent_hash`, the EIP-2935 history-contract slots and the BLOCKHASH
values) is an MPT artifact. To run the SAME blocks under an alternate
commitment scheme, this harness re-seals the chain:

  * the genesis header's state root becomes the scheme's root of the
    fixture pre-state;
  * each valid block is re-executed in order on a full StateDB with its
    `parent_hash` re-linked to the translated parent, and its header is
    re-sealed from that execution — state root under the scheme,
    receipts root / logs bloom / gas used / requests hash from the
    result (hash-reading contracts may legitimately produce different
    receipts once parent hashes change; re-sealing keeps every header
    field consistent with its own chain);
  * `expectException` blocks are carried over UNTRANSLATED: whatever
    made them invalid is preserved (and a stale parent hash can only add
    a second, equally fatal, reason) — accept/reject parity is the
    differential contract, not failure-reason identity;
  * the fixture's `postState` oracle is re-captured from the translated
    replay, so the stateless runner's post-state diff checks the
    translated chain against its own full-state oracle. The VALUE-level
    correctness of execution stays pinned by the untranslated `mpt` run
    of the same fixture — translation only re-derives what is
    commitment-scheme-dependent.

The result is a Fixture whose blocks verify end-to-end under
`--commitment=<scheme>` through the identical stateless machinery
(phant_tpu/spec/runner.py run_fixture_stateless), giving the
accept/reject differential the ISSUE's acceptance criteria pin."""

from __future__ import annotations

from dataclasses import replace
from typing import Dict

from phant_tpu.mpt.mpt import ordered_trie_root
from phant_tpu.spec.fixtures import Fixture, FixtureBlock
from phant_tpu.state.statedb import StateDB
from phant_tpu.types.account import Account
from phant_tpu.types.block import Block


def fork_class_for(network: str):
    """The fork class a fixture network name selects — the single copy of
    the mapping the spec runner and this harness share."""
    net = network.lower()
    if "prague" in net or "osaka" in net:
        from phant_tpu.blockchain.fork import PragueFork

        return PragueFork
    if "cancun" in net:
        from phant_tpu.blockchain.fork import CancunFork

        return CancunFork
    return None


def _snapshot_accounts(state: StateDB) -> Dict[bytes, Account]:
    """Deep-copied post-state oracle of the translated replay (live
    accounts only — deleted entries hold None)."""
    return {
        addr: acct.copy()
        for addr, acct in state.accounts.items()
        if acct is not None
    }


def translate_fixture(fixture: Fixture, scheme) -> Fixture:
    """Re-commit `fixture` under `scheme` (identity for the default
    hexary scheme). Raises on a fixture whose valid blocks fail to
    re-execute — that is a translation bug, never a skip."""
    from phant_tpu.blockchain.chain import Blockchain
    from phant_tpu.utils.trace import metrics

    if scheme.name == "mpt":
        return fixture

    state = StateDB({a: acct.copy() for a, acct in fixture.pre.items()})
    genesis = Block.decode(fixture.genesis_rlp)
    fork_cls = fork_class_for(fixture.network)
    fork = fork_cls(state) if fork_cls is not None else None
    g_header = replace(
        genesis.header, state_root=scheme.state_root_of(state.accounts)
    )
    chain = Blockchain(
        chain_id=1,  # fixtures run on chain id 1 (SpecTest network)
        state=state,
        parent_header=g_header,
        fork=fork,
        verify_state_root=False,
    )

    out_blocks = []
    n_resealed = 0
    for fb in fixture.blocks:
        if fb.expect_exception:
            out_blocks.append(fb)  # untranslated: stays rejected (see above)
            continue
        block = Block.decode(fb.rlp)
        draft_header = replace(
            block.header, parent_hash=chain.parent_header.hash()
        )
        if (
            draft_header.base_fee_per_gas is not None
            and chain.parent_header.base_fee_per_gas is not None
        ):
            # the translated parent's gas_used may legitimately diverge
            # (hash-reading contracts — same reason receipts re-seal), and
            # EIP-1559 derives each base fee from the PARENT's gas usage;
            # re-derive it so the next header validates against its own
            # chain. Identical to the original whenever gas did not
            # diverge (every current fixture).
            from phant_tpu.blockchain.chain import calculate_base_fee

            draft_header = replace(
                draft_header,
                base_fee_per_gas=calculate_base_fee(
                    chain.parent_header.gas_limit,
                    chain.parent_header.gas_used,
                    chain.parent_header.base_fee_per_gas,
                ),
            )
        draft = replace(block, header=draft_header)
        # run_block's shape without the header-vs-execution equality
        # checks: the translated chain re-SEALS those fields instead
        # (a hash-reading contract may produce different receipts here)
        chain.validate_block_header(draft_header)
        state.begin_block()
        try:
            chain.fork.update_parent_block_hash(
                chain.parent_header.block_number, chain.parent_header.hash()
            )
            chain.fork.on_block_start(draft_header)
            result = chain.apply_body(draft)
        except BaseException:
            state.rollback_block()
            raise
        final_header = replace(
            draft_header,
            state_root=scheme.state_root_of(state.accounts),
            receipts_root=ordered_trie_root(
                [r.encode() for r in result.receipts]
            ),
            logs_bloom=result.logs_bloom,
            gas_used=result.gas_used,
            requests_hash=(
                result.requests_hash
                if result.requests_hash is not None
                else draft_header.requests_hash
            ),
        )
        # the FINAL header is what the next block's parent_hash, BLOCKHASH
        # and EIP-2935 history write must see
        chain.parent_header = final_header
        out_blocks.append(
            FixtureBlock(rlp=replace(draft, header=final_header).encode())
        )
        n_resealed += 1

    metrics.count("commitment.translated_fixtures", scheme=scheme.name)
    metrics.count(
        "commitment.translated_blocks", n_resealed, scheme=scheme.name
    )
    return Fixture(
        name=f"{fixture.name}[{scheme.name}]",
        network=fixture.network,
        genesis_rlp=replace(genesis, header=g_header).encode(),
        genesis_header_json=fixture.genesis_header_json,
        blocks=out_blocks,
        last_block_hash=chain.parent_header.hash(),
        pre=fixture.pre,
        post_state=_snapshot_accounts(state),
        seal_engine=fixture.seal_engine,
    )
