"""Tracing, metrics, and profiling.

The reference's only observability is scoped debug logging (reference:
std.log.scoped(.evmone)/(.vm) at src/blockchain/vm.zig:25,130 and the
startup banner at src/main.zig:116-118); evmone's tracer is compiled but
never installed (reference: build.zig:118). This framework upgrades that
slot (SURVEY §5) to:

- `phase(name)` — nestable wall-clock timers aggregated into a process
  metrics registry (count / total / min / max per phase),
- `metrics` — counters (optionally labeled), gauges, fixed-bucket latency
  histograms, and phase timers, with a `report()` table, a deep-copied
  `snapshot()`, and a `prometheus_text()` standard text exposition,
- `span(name, **attrs)` — a thread-safe (thread-local-stacked) per-block
  span tracer: every top-level span emits ONE structured-JSON log line
  carrying its duration, its nested phase timings, and any child spans,
- `trace_context(trace_id)` / `current_trace_id()` — a per-thread request
  identity: the Engine API server opens a context per POST and every span
  opened inside it (on that thread) carries the `trace_id`, so a request's
  span record stays joinable to the scheduler batch that served it even
  after coalescing (phant_tpu/obs/ holds the flight-recorder side),
- `add_span_sink(fn)` — top-level span records additionally fan out to
  registered sinks (the obs flight recorder registers one),
- `jax_profile(logdir)` — a context manager around the JAX profiler for
  device traces of the TPU kernels,
- `scoped_logger(scope)` — the reference's scoped-logger idiom.

Prometheus naming: internal metric names are dotted ("engine_api.requests");
the exposition sanitizes them to `phant_[a-z0-9_]+` families (counters gain
a `_total` suffix, phase timers a `_seconds` summary suffix). Every exported
family must have an entry in METRIC_HELP — `make metrics-lint` enforces it.
"""

from __future__ import annotations

import contextlib
import json
import logging
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple


def scoped_logger(scope: str) -> logging.Logger:
    """(reference: std.log.scoped, e.g. src/blockchain/vm.zig:25)"""
    return logging.getLogger(f"phant_tpu.{scope}")


@dataclass
class TimerStat:
    count: int = 0
    total_s: float = 0.0
    min_s: float = float("inf")
    max_s: float = 0.0

    def add(self, dt: float) -> None:
        self.count += 1
        self.total_s += dt
        self.min_s = min(self.min_s, dt)
        self.max_s = max(self.max_s, dt)

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0


#: default latency buckets (seconds) — sub-ms kernel dispatches up through
#: multi-second stateless executions
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

#: THE request-latency bucket table (engine_api.request_seconds and any
#: future front-door latency family): one module-level constant so call
#: sites can never drift apart on bucket bounds — a histogram's buckets
#: are frozen at first observation, so two call sites with different
#: tables would silently split the family. Extends DEFAULT_BUCKETS with
#: an overload tail (PR 6's open-loop sweeps measured 15s p99s before
#: the stateless gate existed): without buckets past 10s, the derived
#: p99 gauge clamps to the last finite bound exactly when an operator
#: most needs it.
REQUEST_SECONDS_BUCKETS: Tuple[float, ...] = DEFAULT_BUCKETS + (30.0, 60.0)


def histogram_quantile(
    buckets: Sequence[float], counts: Sequence[int], q: float
) -> float:
    """Bucket-interpolated quantile over a fixed-bucket histogram (the
    Prometheus `histogram_quantile` estimate, computed server-side so a
    curl of /metrics answers "what's p99" without a PromQL engine).

    `counts[i]` is the NON-cumulative count for bucket upper bound
    `buckets[i]`, with `counts[-1]` the +Inf overflow slot — the
    Histogram dataclass layout. Linear interpolation inside the target
    bucket (lower bound 0 for the first); a target landing in the +Inf
    slot clamps to the last finite bound (same behavior as PromQL —
    the estimate is a floor there, which is why the exposition also
    carries the exact `_sum`/`_count`). Returns 0.0 for an empty
    histogram. An ESTIMATE by construction: resolution is the bucket
    width around the target rank, never exact order statistics."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    rank = q * total
    cum = 0.0
    for i, ub in enumerate(buckets):
        prev_cum = cum
        cum += counts[i]
        if cum >= rank:
            lo = buckets[i - 1] if i > 0 else 0.0
            if counts[i] <= 0:
                return float(ub)
            frac = (rank - prev_cum) / counts[i]
            return float(lo + (ub - lo) * min(max(frac, 0.0), 1.0))
    # target rank lives in the +Inf slot: clamp to the last finite bound
    return float(buckets[-1]) if buckets else 0.0


@dataclass
class Histogram:
    """Fixed-bucket histogram: cumulative-style exposition is derived at
    render time; `counts[i]` is the count for bucket upper bound
    `buckets[i]`, with one extra slot for +Inf."""

    buckets: Tuple[float, ...] = DEFAULT_BUCKETS
    counts: List[int] = field(default_factory=list)
    sum: float = 0.0
    count: int = 0

    def __post_init__(self) -> None:
        if not self.counts:
            self.counts = [0] * (len(self.buckets) + 1)

    def add(self, value: float) -> None:
        self.sum += value
        self.count += 1
        for i, ub in enumerate(self.buckets):
            if value <= ub:
                self.counts[i] += 1
                return
        self.counts[-1] += 1


def _labels_key(name: str, labels: dict) -> str:
    """Composite storage key `name{k="v",...}` with sorted label names —
    one flat dict keeps snapshot() trivially JSON-able."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


def split_labels(key: str) -> Tuple[str, str]:
    """Inverse of _labels_key: ("name", 'k="v",...') — label part empty
    for unlabeled metrics."""
    if key.endswith("}") and "{" in key:
        name, _, rest = key.partition("{")
        return name, rest[:-1]
    return key, ""


def prometheus_name(name: str) -> str:
    """Sanitize a dotted internal name to a `phant_[a-z0-9_]+` family."""
    s = re.sub(r"[^a-zA-Z0-9_]", "_", name).lower()
    return s if s.startswith("phant_") else "phant_" + s


#: help strings for every exported metric family, keyed by INTERNAL base
#: name (pre-sanitization, no labels). `make metrics-lint` fails the build
#: when an exported family has no entry here — metric-name drift is caught
#: at test time, not on a dashboard.
METRIC_HELP: Dict[str, str] = {
    # engine API server
    "engine_api.requests": "Engine API JSON-RPC requests by method",
    "engine_api.unknown_method": "Engine API requests for unknown methods (one bucket: untrusted strings)",
    "engine_api.request_errors": "Engine API requests answered with a JSON-RPC error or HTTP >= 400",
    "engine_api.client_disconnects": "Engine API responses aborted by client disconnect (BrokenPipe/ConnectionReset)",
    "engine_api.inflight": "Engine API requests currently being handled",
    "engine_api.request_seconds": "Engine API request latency (decode + handle + reply)",
    "engine_api.decode_payload": "JSON -> ExecutionPayload decode phase",
    "engine_api.new_payload": "engine_newPayloadV2/V3/V4 handler phase",
    "engine_api.execute_stateless": "engine_executeStatelessPayloadV1 handler phase",
    # stateless execution
    "stateless.blocks_verified": "Stateless payloads fully executed and root-checked",
    "stateless.errors": "Stateless executions aborted, by exception kind",
    "stateless.witness_verify": "Linked-multiproof witness verification phase",
    "stateless.witness_decode": "Witness -> WitnessStateDB materialization phase",
    "stateless.witness_nodes_decoded": "Witness nodes decoded (digest map built) on the request path — exactly once per payload; a doubled count per payload is a reintroduced second decode",
    "stateless.execute": "Block execution phase over the witness-backed state",
    "stateless.post_root": "Post-state-root recompute phase over the partial trie (host walk or the batched root lane)",
    "stateless.post_root_plan": "Fused account+storage hash-plan build on the request thread (WitnessStateDB.post_root_plan) before root-lane submission",
    "stateless.sig_rows": "Signature-row build on the request thread (TxSigner.signature_rows — host keccak over RLP) before sig-lane submission",
    # memoized witness engine
    "witness_engine.interned_nodes": "Unique trie nodes currently interned in the witness engine",
    "witness_engine.interned_digests": "Unique 32-byte digests currently interned (nodes + child refs)",
    "witness_engine.cache_hits": "Witness nodes served from the interning cache",
    "witness_engine.cache_misses": "Witness nodes that had to be hashed (novel nodes)",
    "witness_engine.evictions": "Generation flushes of the interned set (max_nodes crossed), by tier: deep = shallow pins retained, only deeper tiers evicted; full = everything dropped; twin = python-twin-only flush on a C-core engine",
    "witness_engine.novel_bytes_hashed": "Bytes of novel witness nodes hashed",
    "witness_engine.verify_batch": "Whole verify_batch calls (scan + hash + linkage)",
    "witness_engine.intern": "Interning/scan phase of verify_batch (cache probe + table insert)",
    "witness_engine.hash": "Novel-node keccak phase of verify_batch (includes the C-side commit+join on the finish_native fast path)",
    "witness_engine.linkage_join": "Parent->child linkage join / verdict phase of verify_batch",
    # pipelined two-phase engine API (begin_batch/resolve_batch)
    "witness_engine.prefetch": "Prefetch stage: witness decode + advisory novelty pre-scan + staging pre-fill for the NEXT batch, off the serving critical path (prefetch_batch)",
    "witness_engine.prefetch_plan_hits": "Prefetch plans whose candidate-novel set the authoritative pack-time scan confirmed (staging leases reused)",
    "witness_engine.prefetch_plan_stale": "Prefetch plans dropped stale at pack time (concurrent commit / generation flush) — a perf miss, never a correctness event",
    "witness_engine.pack": "Pack stage: host batch assembly + lock-held intern-table scan (begin_batch); with a prefetch plan, the under-lock re-check + commit only",
    "witness_engine.dispatch": "Dispatch stage: device keccak enqueue of the novel nodes, no host sync (begin_batch)",
    "witness_engine.resolve": "Resolve stage: digest readback/hash outside the lock + commit + linkage join (resolve_batch)",
    # cache_hit_rate vs trie_depth (PHANT_DEPTH_HIST=1): per-depth scan
    # outcome, labels "0".."6", "7+", "u" (unreachable from the root)
    "witness_engine.depth_hits": "Witness-node cache hits by trie depth under the block root (depth-skewed reuse, PAPERS.md 2408.14217)",
    "witness_engine.depth_misses": "Witness-node cache misses (novel nodes) by trie depth under the block root",
    # batched post-state roots (ops/root_engine.py)
    "witness_engine.root_prefetch": "Root-lane prefetch stage: merging a batch's HashPlans into the pooled staging blob OFF the serving critical path (RootEngine.prefetch_batch)",
    "witness_engine.root_pack": "Root-lane pack stage: offload-gate routing + plan merge (or prefetch-merge consumption) (RootEngine.begin_batch)",
    "witness_engine.root_dispatch": "Root-lane dispatch stage: merged-program device enqueue, no host sync",
    "witness_engine.root_resolve": "Root-lane resolve stage: out-row digest readback (device) or the per-plan host mirror",
    "witness_engine.root_batches": "Root batches executed, by backend (device = merged dispatch; host = the offload-gated host walk)",
    "witness_engine.root_requests": "Requests whose post root was computed through the root engine",
    "witness_engine.root_plan_hits": "Root prefetch merges consumed by begin_batch (identity-matched plans list)",
    "witness_engine.root_plan_stale": "Root prefetch merges dropped stale at begin time (shed changed the batch) — a perf miss, never a correctness event",
    # coalesced sender recovery (ops/sig_engine.py)
    "witness_engine.sig_prefetch": "Sig-lane prefetch stage: merging a batch's signature rows + the u256 -> limb encode OFF the serving critical path (SigEngine.prefetch_batch)",
    "witness_engine.sig_pack": "Sig-lane pack stage: offload-gate routing + row merge (or prefetch-merge consumption) (SigEngine.begin_batch)",
    "witness_engine.sig_dispatch": "Sig-lane dispatch stage: merged ecrecover kernel enqueue, no host sync",
    "witness_engine.sig_resolve": "Sig-lane resolve stage: sender-address readback (device) or the fused native batch / scalar fallback over the same merged rows",
    "witness_engine.sig_batches": "Sig batches executed, by backend (device = merged ecrecover dispatch; native/scalar = the offload-gated host routes)",
    "witness_engine.sig_requests": "Requests whose senders were recovered through the sig engine",
    "witness_engine.sig_rows": "Signature rows recovered through the sig engine (the merged-dispatch row counter: rows per batch >> rows per request under coalescing)",
    "witness_engine.sig_plan_hits": "Sig prefetch merges consumed by begin_batch (identity-matched rows list)",
    "witness_engine.sig_plan_stale": "Sig prefetch merges dropped stale at begin time (shed changed the batch) — a perf miss, never a correctness event",
    # device-resident intern table (ops/witness_resident.py)
    "witness_resident.rows": "Rows resident on device (digest + child-ref rows, persistent across batches)",
    "witness_resident.uploaded_nodes": "Truly-novel nodes uploaded to the resident table (after the host prune)",
    "witness_resident.uploaded_bytes": "Truly-novel bytes uploaded to the resident table — the ONLY recurring h2d payload of the resident route",
    "witness_resident.dispatch": "Resident dispatch phase: prune + row assignment + update/verdict enqueue, no host sync",
    "witness_resident.resolve": "Resident resolve phase: verdict (1 B/block) + core-novel digest readback (the honest sync)",
    # continuous-batching scheduler (phant_tpu/serving/)
    "sched.queue_depth": "Verification requests currently in the scheduler admission queue (all lanes)",
    "sched.tenant_queue_depth": "Witness requests currently queued, by tenant lane",
    "sched.batch_size": "Assembled witness-batch sizes (requests per engine dispatch)",
    "sched.queue_wait_seconds": "Admission-to-execution wait per scheduled request",
    "sched.coalesced_requests": "Requests that shared an engine batch with at least one other request",
    "sched.rejected": "Overload rejections by reason (queue_full/tenant_quota/evicted/saturated/deadline/down/shutdown) and tenant",
    "sched.tenant_served": "Requests completed by the scheduler, by tenant (the no-starvation progress counter)",
    "sched.backfill_evictions": "Witness jobs evicted to admit head-of-chain work (backfill first; head-class witness only for a serial mutation), by shed tenant",
    "sched.adaptive_wait_ms": "Current adaptive batching wait chosen by the queue-depth policy (serving/qos.py)",
    "sched.adaptive_wait_adjustments": "Times the adaptive policy changed the assembly wait (shrink under load, widen when idle)",
    "sched.batches": "Scheduler executions by lane (witness batches / serial jobs)",
    "sched.padding_waste": "Unused fraction of the padded device buffer the last witness batch would occupy",
    "sched.executor_crashes": "Scheduler executor crashes (scheduler marked down, /healthz -> 503)",
    "sched.pipeline_depth": "Configured pipeline depth (1 = serialized pack/dispatch/resolve, the pre-pipeline behavior)",
    "sched.pipeline_inflight": "Witness batches currently between begin_batch and resolve_batch",
    "sched.pipeline_stall": "Executor waits for a free pipeline slot (resolve stage is the bottleneck)",
    # 4th pipeline stage: the prefetch worker (PR 9)
    "sched.prefetch_batches": "Witness batches whose decode + novelty pre-scan ran on the prefetch stage (scheduler worker or mesh lane) before pack",
    "sched.prefetch_wait": "Executor waits for a batch's prefetch plan — prefetch cost that did NOT hide under dispatch/resolve (the overlap audit against the witness_engine.prefetch phase)",
    "sched.prefetch_depth": "Assembled witness batches currently waiting on the prefetch worker (the lookahead occupancy)",
    # root lane (batched post-state roots, serving/scheduler.py)
    "sched.root_batches": "Root-lane batches executed by the scheduler, by backend (device/host per the offload gate)",
    "sched.root_coalesced": "Root-lane requests that shared a coalesced root dispatch with at least one other request",
    # sig lane (coalesced sender recovery, serving/scheduler.py)
    "sched.sig_batches": "Sig-lane batches executed by the scheduler, by backend (device/native/scalar per the offload gate)",
    "sched.sig_coalesced": "Sig-lane requests that shared a merged ecrecover dispatch with at least one other request",
    "sched.sig_wait": "Request thread blocks joining its sig-lane senders at execute time — recovery cost that did NOT hide under witness verification (the overlap audit against the witness_engine.sig_* phases)",
    # mesh-sharded dispatch (phant_tpu/serving/mesh_exec.py)
    "sched.mesh_devices": "Device lanes in the mesh executor pool (--sched-mesh)",
    "sched.device_queue_depth": "Witness batches queued on a mesh device lane, by device",
    "sched.device_dispatch": "Witness batches routed to a mesh device lane (device='mesh' = whole-mesh megabatch), by device",
    "sched.device_stall": "Scheduler waits for a free mesh lane slot (every device at its bound)",
    "sched.mesh_megabatches": "Full single-bucket batches dispatched as one whole-mesh sharded fused kernel call",
    "sched.megabatch_backlog_triggers": "Megabatches fired by the backlog-depth trigger (queued same-bucket work >= mesh width x k) rather than a full batch",
    # per-lane device-busy accounting (phant_tpu/obs/busy.py)
    "sched.device_busy_pct": "Rolling-window device-busy percentage per lane (device='mesh' = whole-mesh megabatch dispatches): the two-phase begin/resolve protocol brackets device occupancy, integrated as a union of in-flight intervals — 'the chip idles 60% at depth 1' read directly off /metrics or /healthz",
    # observability layer (phant_tpu/obs/)
    "sched.watchdog_stalls": "Executor stalls detected by the obs watchdog (in-flight batch past its deadline)",
    "flight.dumps": "Flight-recorder postmortem dumps written, by trigger reason",
    # per-request critical-path attribution (phant_tpu/obs/critpath.py)
    "critpath.phase_seconds": "Per-request critical-path phase time at verify_block span close, by phase (sig_rows/queue_wait/prefetch/pack/dispatch/resolve/witness_decode/sig_wait/evm/root_plan/root_wait/post_root) — phases tile the request's wall clock; derived from the span's own phase timers plus the batch records the serving lanes attach",
    "critpath.wall_seconds": "verify_block request wall clock as seen by the critical-path rollup (the denominator of the coverage gauges)",
    "critpath.unattributed_seconds": "Per-request residual the phase tiling could NOT attribute (span overhead, gaps between phases) — the honesty check's raw series",
    "critpath.coverage_pct": "Cumulative attributed share of verify_block wall clock (the >=95% acceptance surface: anything lower means the phase tiling is missing a real cost)",
    "critpath.unattributed_pct": "Cumulative UNattributed share of verify_block wall clock (100 - coverage) — the honesty-check residual gauge",
    "critpath.requests": "verify_block spans rolled up by the critical-path attribution sink",
    "obs.slow_captures": "Requests captured into the /debug/slow flight ring, by trigger (wall = --slo-budget-ms exceeded; near = landed in the top PHANT_SLO_NEAR_PCT of the budget, sampled; a phase name = that phase's env budget exceeded)",
    # unified timeline export (phant_tpu/obs/timeline.py)
    "obs.timeline_kept": "Requests kept by the timeline tail-sampler at span close, by reason (error = crashed request, slo = wall budget blown, p99 = rolling per-phase p99 exemplar, sample = uniform 1-in-N)",
    "obs.timeline_dropped": "Requests dropped by the timeline tail-sampler, by reason (sampled_out = span-close decision — kept + sampled_out reconciles with offered load; ring_full = a previously-KEPT entry evicted by ring overflow, counted separately)",
    "obs.timeline_exports": "Timeline exports rendered (GET /debug/timeline and the optional spool-to-dir copies)",
    # commitment schemes (phant_tpu/commitment/)
    "commitment.state_views": "Witness-backed state views constructed, by commitment scheme (mpt/binary) — the per-request scheme selector's audit trail",
    "commitment.witness_nodes": "Witness nodes generated by full-state witness collection (spec runner / differential harnesses), by scheme",
    "commitment.translated_fixtures": "Spec fixtures re-committed under an alternate commitment scheme (commitment/translate.py)",
    "commitment.translated_blocks": "Fixture blocks re-sealed with alternate-scheme state roots during fixture translation",
    # historical replay engine (phant_tpu/replay/)
    "replay.segments": "Chain segments imported by the replay engine (prefetch/pack/dispatch/resolve pipeline turns)",
    "replay.blocks": "Blocks successfully imported by the replay engine",
    "replay.txs": "Transactions imported by the replay engine (the merged-ecrecover row volume)",
    "replay.block_failures": "Consensus-invalid blocks that stopped a replay (the replay.block_failed flight record carries the attribution)",
    "replay.lane_fallbacks": "Segments degraded to a local megabatch after a scheduler-lane failure, by stage (prefetch/pack/dispatch/resolve; -32052 in-flight-only semantics)",
    "replay.root_groups": "Deferred segment-root groups resolved, by backend (device = one vmapped _hash_plans_batched program per structure-sharing run; host = singleton/unplannable walks)",
    "replay.prefetch": "Replay prefetch stage: building segment N+1's merged signature rows (host keccak over RLP) under segment N's EVM execution",
    "replay.pack": "Replay pack stage: submitting segment N+1's witness megabatch to the witness lane",
    "replay.dispatch": "Replay dispatch stage: launching segment N+1's merged ecrecover on the sig lane (incl. the sig-backlog pacing wait)",
    "replay.sig_wait": "Replay blocks joining a segment's merged senders at execute time — recovery cost that did NOT hide under the previous segment's EVM (the overlap audit)",
    "replay.witness_wait": "Replay blocks joining a segment's witness verdicts at execute time",
    "replay.root_wait": "Deferred segment-root lowering + readback at segment end (the one root sync per segment)",
    "replay.segment_seconds": "Whole-segment resolve+execute wall clock (the blocks/s denominator at segment granularity)",
    "replay.segment_blocks": "Configured blocks per replay segment (--segment)",
    "replay.pipeline_depth": "Configured replay pipeline depth (1 = fully inline; >= 2 = segment N+1 prepared under segment N's execution)",
    # crypto backend dispatch
    "keccak.batches": "Batched keccak dispatches by backend",
    "keccak.bytes": "Payload bytes submitted to batched keccak by backend",
    "keccak.device_dispatch": "Host->device upload + kernel dispatch phase",
    "keccak.host_readback": "Device->host digest readback (the honest sync) phase",
    "backend.selected": "Crypto-backend selections by backend (process start + bench flips)",
    "backend.offload_decisions": "Adaptive offload-gate verdicts by outcome (device/native)",
}


#: the trace vocabulary: every `span(name, ...)` name and every flight-event
#: kind (`flight.record(kind, ...)`, phant_tpu/obs/flight.py) must have an
#: entry here — phantlint's SPANNAME rule enforces it exactly the way
#: METRICNAME enforces METRIC_HELP, so span/flight names stay literal,
#: documented, and free of dead catalog entries.
SPAN_HELP: Dict[str, str] = {
    # spans (top-level records carry trace_id + the scheduler batch fields)
    "verify_block": "One stateless payload execution: witness_verify/witness_decode/execute/post_root phases plus the serving batch fields (batch_id, queue_wait_ms, ...)",
    # flight-event kinds (phant_tpu/obs/flight.py ring records)
    "span": "A completed top-level span record (mirrored from the span sink)",
    "error": "An exception record (stateless execution aborts and other instrumented failures)",
    "sched.admit": "A request admitted to the scheduler queue (carries tenant + priority)",
    "sched.shed": "A request shed at admission, execution, or the stateless concurrency gate (queue_full/tenant_quota/evicted/saturated/deadline/down/shutdown; carries the shed tenant)",
    "sched.adapt_wait": "The adaptive batching policy changed the assembly wait (old/new wait + queue depth)",
    "sched.batch_start": "The executor picked up a batch (witness lane) or serial job",
    "sched.batch_done": "A batch/serial job finished; carries the batch record (size, bucket, backend, cache counts, trace ids)",
    "sched.executor_crash": "The scheduler executor died; carries the crashing batch's ids",
    "sched.stall": "The obs watchdog found the in-flight batch past its deadline",
    "flight.dump": "A postmortem dump was written to disk (reason + path)",
    "obs.slow_capture": "A request blew its SLO budget (--slo-budget-ms wall clock, or a per-phase env override): carries the FULL span tree plus the critical-path breakdown — metrics say THAT it was slow, this exemplar says WHY (served at /debug/slow)",
    "obs.profile": "An on-demand TPU profiler capture ran (POST /debug/profile): carries the trace directory, the captured window, and the artifact count",
    "obs.timeline_export": "A timeline export was rendered (GET /debug/timeline / spool): carries the window, event count, and how many requests/batches landed in it",
    "replay.segment_crash": "A scheduler lane failed a replay segment's in-flight work (stage-named: prefetch/pack/dispatch/resolve; carries the SchedulerDown/-32052 code); the segment degraded to its local megabatch fallback and the import continued",
    "replay.block_failed": "A consensus-invalid block stopped a replay import (stage-named; carries the block index/number and the BlockError text) — earlier blocks stand, run_blocks semantics",
}


class Metrics:
    """Process-global counters, gauges, histograms, and phase timers
    (thread-safe; `snapshot()` deep-copies under the lock so exposition
    never reads torn values)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._timers: Dict[str, TimerStat] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, Histogram] = {}

    def count(self, name: str, delta: int = 1, **labels) -> None:
        key = _labels_key(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + delta

    def gauge_set(self, name: str, value: float, **labels) -> None:
        key = _labels_key(name, labels)
        with self._lock:
            self._gauges[key] = value

    def gauge_add(self, name: str, delta: float, **labels) -> None:
        key = _labels_key(name, labels)
        with self._lock:
            self._gauges[key] = self._gauges.get(key, 0) + delta

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            self._timers.setdefault(name, TimerStat()).add(seconds)
        sp = current_span()
        if sp is not None:
            sp.add_phase(name, seconds)

    def observe_hist(
        self,
        name: str,
        value: float,
        buckets: Optional[Tuple[float, ...]] = None,
        **labels,
    ) -> None:
        key = _labels_key(name, labels)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = Histogram(buckets or DEFAULT_BUCKETS)
            h.add(value)

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        """Time a phase: `with metrics.phase("engine_api.new_payload"): ...`"""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    def snapshot(self) -> dict:
        """Deep copy of every table under the lock: TimerStat/Histogram
        objects keep mutating concurrently, and exposition must never read
        a torn (count updated, sum not yet) pair."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "timers": {
                    k: {
                        "count": v.count,
                        "total_s": v.total_s,
                        "mean_s": v.mean_s,
                        "min_s": v.min_s,
                        "max_s": v.max_s,
                    }
                    for k, v in self._timers.items()
                },
                "gauges": dict(self._gauges),
                "histograms": {
                    k: {
                        "buckets": list(h.buckets),
                        "counts": list(h.counts),
                        "sum": h.sum,
                        "count": h.count,
                    }
                    for k, h in self._hists.items()
                },
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._timers.clear()
            self._gauges.clear()
            self._hists.clear()

    def report(self) -> str:
        """Box table of every phase/counter (same presentation family as the
        chain-config dump, reference: src/config/config.zig:67-90)."""
        snap = self.snapshot()
        rows = [("metric", "count", "total", "mean")]
        for name, c in sorted(snap["counters"].items()):
            rows.append((name, str(c), "-", "-"))
        for name, g in sorted(snap["gauges"].items()):
            rows.append((name, f"{g:g}", "-", "-"))
        for name, h in sorted(snap["histograms"].items()):
            rows.append((name, str(h["count"]), f"{h['sum'] * 1e3:.2f}ms", "-"))
        for name, t in sorted(snap["timers"].items()):
            rows.append(
                (
                    name,
                    str(t["count"]),
                    f"{t['total_s'] * 1e3:.2f}ms",
                    f"{t['mean_s'] * 1e3:.3f}ms",
                )
            )
        widths = [max(len(r[i]) for r in rows) for i in range(4)]

        def line(l, m, r):
            return l + m.join("─" * (w + 2) for w in widths) + r

        out = [line("┌", "┬", "┐")]
        for i, row in enumerate(rows):
            out.append("│" + "│".join(f" {c.ljust(w)} " for c, w in zip(row, widths)) + "│")
            if i == 0:
                out.append(line("├", "┼", "┤"))
        out.append(line("└", "┴", "┘"))
        return "\n".join(out)

    # -- Prometheus text exposition -----------------------------------------

    def prometheus_text(self) -> str:
        """Standard Prometheus text exposition (version 0.0.4) of every
        table. Counters export as `<family>_total`, phase timers as
        `<family>_seconds` summaries (count/sum), histograms with
        cumulative `_bucket{le=...}` series."""
        snap = self.snapshot()
        out: List[str] = []
        emitted_help: set = set()

        def header(base: str, family: str, mtype: str) -> None:
            if family in emitted_help:
                return
            emitted_help.add(family)
            help_s = METRIC_HELP.get(base)
            if help_s:
                out.append(f"# HELP {family} {help_s}")
            out.append(f"# TYPE {family} {mtype}")

        def fmt(v: float) -> str:
            return repr(v) if isinstance(v, float) else str(v)

        # group labeled series under one family so HELP/TYPE emit once
        for key in sorted(snap["counters"]):
            base, labels = split_labels(key)
            family = prometheus_name(base)
            if not family.endswith("_total"):
                family += "_total"
            header(base, family, "counter")
            lab = f"{{{labels}}}" if labels else ""
            out.append(f"{family}{lab} {snap['counters'][key]}")
        for key in sorted(snap["gauges"]):
            base, labels = split_labels(key)
            family = prometheus_name(base)
            header(base, family, "gauge")
            lab = f"{{{labels}}}" if labels else ""
            out.append(f"{family}{lab} {fmt(snap['gauges'][key])}")
        for key in sorted(snap["histograms"]):
            base, labels = split_labels(key)
            family = prometheus_name(base)
            header(base, family, "histogram")
            h = snap["histograms"][key]
            cum = 0
            for ub, c in zip(h["buckets"], h["counts"]):
                cum += c
                lab = f'le="{fmt(float(ub))}"' + (f",{labels}" if labels else "")
                out.append(f"{family}_bucket{{{lab}}} {cum}")
            lab = 'le="+Inf"' + (f",{labels}" if labels else "")
            out.append(f"{family}_bucket{{{lab}}} {h['count']}")
            lab = f"{{{labels}}}" if labels else ""
            out.append(f"{family}_sum{lab} {fmt(h['sum'])}")
            out.append(f"{family}_count{lab} {h['count']}")
        # derived p50/p99 gauges per histogram family: bucket-interpolated
        # at scrape time (histogram_quantile above — an estimate bounded
        # by bucket resolution, never exact order statistics; the raw
        # bucket series stay the authoritative data). Emitted as separate
        # `<family>_p50`/`<family>_p99` gauge families so a dashboard-less
        # operator can read quantiles straight off a curl.
        for q, suffix in ((0.5, "_p50"), (0.99, "_p99")):
            for key in sorted(snap["histograms"]):
                h = snap["histograms"][key]
                if h["count"] <= 0:
                    continue
                base, labels = split_labels(key)
                family = prometheus_name(base) + suffix
                if family not in emitted_help:
                    emitted_help.add(family)
                    out.append(
                        f"# HELP {family} bucket-interpolated "
                        f"p{int(q * 100)} of {prometheus_name(base)} "
                        "(derived at scrape; estimate, not exact)"
                    )
                    out.append(f"# TYPE {family} gauge")
                lab = f"{{{labels}}}" if labels else ""
                v = histogram_quantile(h["buckets"], h["counts"], q)
                out.append(f"{family}{lab} {fmt(float(v))}")
        for key in sorted(snap["timers"]):
            base, labels = split_labels(key)
            family = prometheus_name(base)
            if not family.endswith("_seconds"):
                family += "_seconds"
            header(base, family, "summary")
            lab = f"{{{labels}}}" if labels else ""
            t = snap["timers"][key]
            out.append(f"{family}_sum{lab} {fmt(t['total_s'])}")
            out.append(f"{family}_count{lab} {t['count']}")
        return "\n".join(out) + "\n"


#: process-global registry (importable singleton)
metrics = Metrics()


def phase(name: str):
    """Module-level shorthand for `metrics.phase(name)`."""
    return metrics.phase(name)


# ---------------------------------------------------------------------------
# span tracing
# ---------------------------------------------------------------------------

_span_log = logging.getLogger("phant_tpu.span")
_span_tls = threading.local()

#: top-level span records (dicts) fan out here in addition to the log line;
#: the obs flight recorder registers a sink (phant_tpu/obs/__init__.py).
#: Mutated only via add/remove below; iteration reads a snapshot reference.
_span_sinks: List = []


def add_span_sink(fn) -> None:
    """Register `fn(record: dict)` to receive every TOP-LEVEL span record.
    Idempotent per function object. Sinks must be fast and non-raising
    (exceptions are swallowed: tracing must never fail the traced work)."""
    if fn not in _span_sinks:
        _span_sinks.append(fn)


def remove_span_sink(fn) -> None:
    if fn in _span_sinks:
        _span_sinks.remove(fn)


def new_trace_id() -> str:
    """16-hex-char request identity (collision-safe at serving volumes)."""
    import os as _os

    return _os.urandom(8).hex()


def current_trace_id() -> Optional[str]:
    """The trace id of the innermost open trace_context on this thread."""
    stack = getattr(_span_tls, "trace_ids", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def trace_context(trace_id: Optional[str] = None) -> Iterator[str]:
    """Bind a request identity to the current thread: spans opened inside
    (and scheduler submissions made inside, phant_tpu/serving/) carry this
    `trace_id`. Nests; the Engine API server opens one per POST."""
    tid = trace_id or new_trace_id()
    stack = getattr(_span_tls, "trace_ids", None)
    if stack is None:
        stack = _span_tls.trace_ids = []
    stack.append(tid)
    try:
        yield tid
    finally:
        stack.pop()


class Span:
    """One traced operation: wall-clock duration + the phase timings that
    ran inside it (fed by Metrics.observe) + any child spans. Spans stack
    per-thread (thread-local), which is the thread-safety mechanism —
    concurrent request threads each trace their own block without locking."""

    __slots__ = ("name", "attrs", "duration_s", "phases", "children")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.duration_s = 0.0
        self.phases: Dict[str, List[float]] = {}  # name -> [count, total_s]
        self.children: List[dict] = []

    def add_phase(self, name: str, seconds: float) -> None:
        st = self.phases.get(name)
        if st is None:
            self.phases[name] = [1, seconds]
        else:
            st[0] += 1
            st[1] += seconds

    def to_dict(self) -> dict:
        d: dict = {"span": self.name, **self.attrs}
        d["duration_ms"] = round(self.duration_s * 1e3, 3)
        if self.phases:
            d["phases"] = {
                k: {"count": c, "total_ms": round(t * 1e3, 3)}
                for k, (c, t) in self.phases.items()
            }
        if self.children:
            d["children"] = self.children
        return d


def current_span() -> Optional[Span]:
    stack = getattr(_span_tls, "stack", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def span(name: str, **attrs) -> Iterator[Span]:
    """Trace one operation: `with span("verify_block", block=n): ...`.

    Phase timings recorded inside (via `metrics.phase` / `observe`) attach
    to the innermost open span of the current thread. A nested span folds
    its summary into its parent; each TOP-LEVEL span emits one
    structured-JSON log line (logger `phant_tpu.span`, INFO) with the
    nested phase timings — the per-block trace record — and fans the same
    record out to registered span sinks (the obs flight recorder). A span
    opened inside a `trace_context` carries its `trace_id`."""
    if "trace_id" not in attrs:
        tid = current_trace_id()
        if tid is not None:
            attrs["trace_id"] = tid
    sp = Span(name, attrs)
    stack = getattr(_span_tls, "stack", None)
    if stack is None:
        stack = _span_tls.stack = []
    stack.append(sp)
    t0 = time.perf_counter()
    try:
        yield sp
    finally:
        sp.duration_s = time.perf_counter() - t0
        stack.pop()
        if stack:
            stack[-1].children.append(sp.to_dict())
        else:
            sinks = tuple(_span_sinks)  # snapshot: a concurrent
            # remove_span_sink must not shift the list mid-iteration
            if sinks or _span_log.isEnabledFor(logging.INFO):
                # serialization is per-block work on the serving hot path —
                # skip it entirely when nobody listens
                record = sp.to_dict()
                for sink in sinks:
                    try:
                        sink(record)
                    except Exception:  # tracing must never fail the work
                        pass
                if _span_log.isEnabledFor(logging.INFO):
                    _span_log.info(json.dumps(record, default=str))


@contextlib.contextmanager
def jax_profile(logdir: Optional[str] = None) -> Iterator[None]:
    """Capture a JAX/XLA device trace (view with TensorBoard or Perfetto);
    no-op when logdir is None so call sites can be left in production code."""
    if logdir is None:
        yield
        return
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
