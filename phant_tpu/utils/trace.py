"""Tracing, metrics, and profiling.

The reference's only observability is scoped debug logging (reference:
std.log.scoped(.evmone)/(.vm) at src/blockchain/vm.zig:25,130 and the
startup banner at src/main.zig:116-118); evmone's tracer is compiled but
never installed (reference: build.zig:118). This framework upgrades that
slot (SURVEY §5) to:

- `phase(name)` — nestable wall-clock timers aggregated into a process
  metrics registry (count / total / min / max per phase),
- `metrics` — counters + timers with a `report()` table and `snapshot()`,
- `jax_profile(logdir)` — a context manager around the JAX profiler for
  device traces of the TPU kernels,
- `scoped_logger(scope)` — the reference's scoped-logger idiom.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, Optional


def scoped_logger(scope: str) -> logging.Logger:
    """(reference: std.log.scoped, e.g. src/blockchain/vm.zig:25)"""
    return logging.getLogger(f"phant_tpu.{scope}")


@dataclass
class TimerStat:
    count: int = 0
    total_s: float = 0.0
    min_s: float = float("inf")
    max_s: float = 0.0

    def add(self, dt: float) -> None:
        self.count += 1
        self.total_s += dt
        self.min_s = min(self.min_s, dt)
        self.max_s = max(self.max_s, dt)

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0


class Metrics:
    """Process-global counters and phase timers (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._timers: Dict[str, TimerStat] = {}

    def count(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + delta

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            self._timers.setdefault(name, TimerStat()).add(seconds)

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        """Time a phase: `with metrics.phase("engine_api.new_payload"): ...`"""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "timers": {
                    k: {
                        "count": v.count,
                        "total_s": v.total_s,
                        "mean_s": v.mean_s,
                        "min_s": v.min_s,
                        "max_s": v.max_s,
                    }
                    for k, v in self._timers.items()
                },
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._timers.clear()

    def report(self) -> str:
        """Box table of every phase/counter (same presentation family as the
        chain-config dump, reference: src/config/config.zig:67-90)."""
        snap = self.snapshot()
        rows = [("metric", "count", "total", "mean")]
        for name, c in sorted(snap["counters"].items()):
            rows.append((name, str(c), "-", "-"))
        for name, t in sorted(snap["timers"].items()):
            rows.append(
                (
                    name,
                    str(t["count"]),
                    f"{t['total_s'] * 1e3:.2f}ms",
                    f"{t['mean_s'] * 1e3:.3f}ms",
                )
            )
        widths = [max(len(r[i]) for r in rows) for i in range(4)]

        def line(l, m, r):
            return l + m.join("─" * (w + 2) for w in widths) + r

        out = [line("┌", "┬", "┐")]
        for i, row in enumerate(rows):
            out.append("│" + "│".join(f" {c.ljust(w)} " for c, w in zip(row, widths)) + "│")
            if i == 0:
                out.append(line("├", "┼", "┤"))
        out.append(line("└", "┴", "┘"))
        return "\n".join(out)


#: process-global registry (importable singleton)
metrics = Metrics()


def phase(name: str):
    """Module-level shorthand for `metrics.phase(name)`."""
    return metrics.phase(name)


@contextlib.contextmanager
def jax_profile(logdir: Optional[str] = None) -> Iterator[None]:
    """Capture a JAX/XLA device trace (view with TensorBoard or Perfetto);
    no-op when logdir is None so call sites can be left in production code."""
    if logdir is None:
        yield
        return
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
