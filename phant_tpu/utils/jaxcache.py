"""Persistent XLA compilation cache (shared by bench.py and the tests).

The kernels are identical across processes; recompiling the 256-step
ecrecover ladder per run costs minutes. Best-effort: older jax without the
persistent cache just runs uncached."""

from __future__ import annotations

import os


def enable_compile_cache(cache_dir: str | None = None) -> None:
    try:
        import jax

        cache = cache_dir or os.environ.get(
            "PHANT_JAX_CACHE",
            os.path.join(os.path.dirname(__file__), "..", "..", "build", "jax_cache"),
        )
        cache = os.path.abspath(cache)
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass
