"""Persistent XLA compilation cache (shared by bench.py and the driver
entry points). Thin wrapper over the single implementation in
phant_tpu/ops/_cache.py — see its docstring for the opt-out contract
(PHANT_NO_COMPILE_CACHE=1; tests set it because concurrent writers can
corrupt entries and jax segfaults on a corrupt cache)."""

from __future__ import annotations

import os


def enable_compile_cache(cache_dir: str | None = None) -> None:
    if cache_dir:
        # an explicit dir is an isolation request — it outranks any
        # inherited PHANT_JAX_CACHE
        os.environ["PHANT_JAX_CACHE"] = os.path.abspath(cache_dir)
    from phant_tpu.ops._cache import enable_compilation_cache

    enable_compilation_cache()
