"""Hex helpers for 0x-prefixed JSON encodings (Engine API, chainspecs, fixtures).

Equivalent surface to the reference's hex utilities
(reference: src/common/hexutils.zig:9-77).
"""

from __future__ import annotations

__all__ = [
    "hex_to_bytes",
    "hex_to_int",
    "hex_to_address",
    "hex_to_hash",
    "int_to_hex",
    "bytes_to_hex",
]


def hex_to_bytes(value: str) -> bytes:
    """Decode a 0x-prefixed (or bare) hex string; odd-length inputs are
    left-padded with one zero nibble (fixture JSONs contain e.g. "0x1")."""
    if value.startswith(("0x", "0X")):
        value = value[2:]
    if len(value) % 2:
        value = "0" + value
    return bytes.fromhex(value)


def hex_to_int(value: str) -> int:
    if isinstance(value, int):
        return value
    if value in ("0x", ""):
        return 0
    return int(value, 16)


def hex_to_address(value: str) -> bytes:
    raw = hex_to_bytes(value)
    if len(raw) > 20:
        raise ValueError(f"address too long: {value}")
    return raw.rjust(20, b"\x00")


def hex_to_hash(value: str) -> bytes:
    raw = hex_to_bytes(value)
    if len(raw) > 32:
        raise ValueError(f"hash too long: {value}")
    return raw.rjust(32, b"\x00")


def int_to_hex(value: int) -> str:
    return hex(value)


def bytes_to_hex(value: bytes) -> str:
    return "0x" + value.hex()
