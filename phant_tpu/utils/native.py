"""Loader/builder for the native C++ runtime (native/*.cc -> libphant_native.so).

The reference builds its native components (ethash keccak, evmone, secp256k1)
as static libs inside build.zig (reference: build.zig:79-135). Here the native
runtime is a single shared library compiled on demand with g++ and loaded via
ctypes; if the toolchain is unavailable the pure-Python fallbacks take over.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import List, Optional, Sequence

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent
_NATIVE_DIR = _REPO_ROOT / "native"
_BUILD_DIR = _REPO_ROOT / "build"
_LIB_PATH = _BUILD_DIR / "libphant_native.so"

_lock = threading.Lock()
_loaded: Optional["NativeLib"] = None
_load_failed = False


def _sources() -> List[Path]:
    # selftest.cc is the standalone sanitizer harness (`make sanitize`);
    # pyext.cc is the CPython extension (its own .so, load_engine_ext) —
    # neither belongs in the ctypes shared library
    return sorted(
        p
        for p in _NATIVE_DIR.glob("*.cc")
        if p.name not in ("selftest.cc", "pyext.cc")
    )


def _needs_rebuild() -> bool:
    if not _LIB_PATH.exists():
        return True
    lib_mtime = _LIB_PATH.stat().st_mtime
    return any(src.stat().st_mtime > lib_mtime for src in _sources())


def _arch_flags() -> list:
    """-march=native only where it exists: aarch64 gcc spells it -mcpu and
    cross-builds (reference CI cross-compiles aarch64, its ci.yml) must not
    die on an x86-only flag. PHANT_NATIVE_ARCH_FLAGS overrides outright."""
    import os
    import platform

    override = os.environ.get("PHANT_NATIVE_ARCH_FLAGS")
    if override is not None:
        return override.split()
    machine = platform.machine().lower()
    if machine in ("x86_64", "amd64", "i686"):
        return ["-march=native"]
    if machine in ("aarch64", "arm64"):
        return ["-mcpu=native"]
    return []


def build_native(verbose: bool = False) -> Path:
    """Compile native/*.cc into build/libphant_native.so (idempotent)."""
    _BUILD_DIR.mkdir(exist_ok=True)
    if _needs_rebuild():
        cmd = [
            "g++", "-O3", *_arch_flags(), "-std=c++20", "-shared", "-fPIC",
            "-fno-exceptions", "-fno-rtti", "-Wall",
            *(str(s) for s in _sources()),
            "-o", str(_LIB_PATH),
        ]
        if verbose:
            print("[phant_tpu.native]", " ".join(cmd))
        subprocess.run(cmd, check=True, capture_output=not verbose)
    return _LIB_PATH


class NativeLib:
    """ctypes facade over the native runtime."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        lib.phant_keccak256.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ]
        lib.phant_keccak256.restype = None
        lib.phant_keccak256_batch.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_size_t,
            ctypes.c_char_p,
        ]
        lib.phant_keccak256_batch.restype = None
        self.has_fast_keccak = hasattr(lib, "phant_keccak256_batch_fast")
        if self.has_fast_keccak:
            lib.phant_keccak256_batch_fast.argtypes = (
                lib.phant_keccak256_batch.argtypes
            )
            lib.phant_keccak256_batch_fast.restype = None
        lib.phant_pack_keccak.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_size_t,
            ctypes.c_size_t,
            ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        lib.phant_pack_keccak.restype = ctypes.c_int
        lib.phant_scan_refs.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_size_t,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_size_t,
        ]
        lib.phant_scan_refs.restype = ctypes.c_long
        lib.phant_ecrecover.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_int32, ctypes.c_char_p,
        ]
        lib.phant_ecrecover.restype = ctypes.c_int32
        lib.phant_ecrecover_batch.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_char_p,
        ]
        lib.phant_ecrecover_batch.restype = None
        self.has_engine = hasattr(lib, "phant_engine_new")
        if self.has_engine:
            lib.phant_engine_new.argtypes = []
            lib.phant_engine_new.restype = ctypes.c_void_p
            lib.phant_engine_free.argtypes = [ctypes.c_void_p]
            lib.phant_engine_free.restype = None
            lib.phant_engine_flush.argtypes = [ctypes.c_void_p]
            lib.phant_engine_flush.restype = None
            lib.phant_engine_nodes.argtypes = [ctypes.c_void_p]
            lib.phant_engine_nodes.restype = ctypes.c_uint64
            lib.phant_engine_digests.argtypes = [ctypes.c_void_p]
            lib.phant_engine_digests.restype = ctypes.c_uint64
            lib.phant_engine_scan.argtypes = [ctypes.c_void_p] + [
                ctypes.c_void_p
            ] * 3 + [ctypes.c_uint64, ctypes.c_void_p, ctypes.c_void_p,
                     ctypes.c_void_p]
            lib.phant_engine_scan.restype = ctypes.c_int
            lib.phant_engine_commit.argtypes = [ctypes.c_void_p] + [
                ctypes.c_void_p
            ] * 3 + [ctypes.c_uint64, ctypes.c_void_p, ctypes.c_void_p,
                     ctypes.c_uint64, ctypes.c_char_p]
            lib.phant_engine_commit.restype = ctypes.c_int64
            lib.phant_engine_verdict.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_uint64, ctypes.c_char_p, ctypes.c_void_p,
            ]
            lib.phant_engine_verdict.restype = ctypes.c_int

    def keccak256(self, data: bytes) -> bytes:
        out = ctypes.create_string_buffer(32)
        self._lib.phant_keccak256(data, len(data), out)
        return out.raw

    @staticmethod
    def _layout(payloads: Sequence[bytes]):
        """Concatenate payloads and build the C-ABI (blob, offsets, lens).
        The length/offset tables come from numpy (fromiter + cumsum) — the
        old per-item Python loop cost more than the C keccak it fed at
        witness novel-batch sizes (~10k items)."""
        import numpy as np

        n = len(payloads)
        blob = b"".join(payloads)
        lens_np = np.fromiter(map(len, payloads), np.uint32, n)
        offsets_np = np.zeros(n, np.uint64)
        if n > 1:
            np.cumsum(lens_np[:-1], dtype=np.uint64, out=offsets_np[1:])
        offsets = (ctypes.c_uint64 * n).from_buffer(offsets_np)
        lens = (ctypes.c_uint32 * n).from_buffer(lens_np)
        return blob, offsets, lens

    def pack_keccak(self, payloads: Sequence[bytes], max_chunks: int):
        """Pad+chunk payloads into the device keccak layout.

        Returns (buf (B, max_chunks*136) u8 ndarray, nchunks (B,) i32 ndarray);
        the caller reshapes/views into (B, C, 34) u32 words."""
        import numpy as np

        n = len(payloads)
        blob, offsets, lens = self._layout(payloads)
        buf = np.zeros((n, max_chunks * 136), dtype=np.uint8)
        nchunks = np.zeros((n,), dtype=np.int32)
        rc = self._lib.phant_pack_keccak(
            blob,
            offsets,
            lens,
            n,
            max_chunks,
            buf.ctypes.data_as(ctypes.c_void_p),
            nchunks.ctypes.data_as(ctypes.c_void_p),
        )
        if rc != 0:
            raise ValueError(f"payload exceeds bucket bound {max_chunks}")
        return buf, nchunks

    def ecrecover(self, msg_hash: bytes, r: int, s: int, recid: int) -> Optional[bytes]:
        """64-byte uncompressed pubkey (X||Y) or None if unrecoverable
        (reference scope: src/crypto/ecdsa.zig:19-26 via libsecp256k1)."""
        out = ctypes.create_string_buffer(64)
        rc = self._lib.phant_ecrecover(
            msg_hash, r.to_bytes(32, "big"), s.to_bytes(32, "big"), recid, out
        )
        return out.raw if rc == 0 else None

    def ecrecover_batch(self, msg_hashes, rs, ss, recids):
        """[(address|None)] for each signature: recover + keccak + slice."""
        n = len(msg_hashes)
        if n == 0:
            return []
        msgs = b"".join(msg_hashes)
        r_blob = b"".join(v.to_bytes(32, "big") for v in rs)
        s_blob = b"".join(v.to_bytes(32, "big") for v in ss)
        recid_arr = (ctypes.c_int32 * n)(*recids)
        addrs = ctypes.create_string_buffer(20 * n)
        ok = ctypes.create_string_buffer(n)
        self._lib.phant_ecrecover_batch(
            msgs, r_blob, s_blob, recid_arr, n, addrs, ok
        )
        raw, okb = addrs.raw, ok.raw
        return [raw[20 * i : 20 * i + 20] if okb[i] else None for i in range(n)]

    def scan_refs(self, blob, offsets, lens):
        """Child-ref scan over RLP trie nodes laid out in `blob` (numpy
        arrays: offsets u64, lens u32). Returns (ref_off i64, ref_node i32)
        numpy arrays, or raises ValueError on malformed RLP."""
        import numpy as np

        offsets = np.ascontiguousarray(offsets, np.uint64)
        lens = np.ascontiguousarray(lens, np.uint32)
        n = len(offsets)
        cap = max(int(lens.sum()) // 33 + 17, 17)  # >= max possible refs
        ref_off = np.empty(cap, np.int64)
        ref_node = np.empty(cap, np.int32)
        blob = np.ascontiguousarray(blob, dtype=np.uint8)
        cnt = self._lib.phant_scan_refs(
            blob.ctypes.data_as(ctypes.c_void_p),
            offsets.ctypes.data_as(ctypes.c_void_p),
            lens.ctypes.data_as(ctypes.c_void_p),
            n,
            ref_off.ctypes.data_as(ctypes.c_void_p),
            ref_node.ctypes.data_as(ctypes.c_void_p),
            cap,
        )
        if cnt < 0:
            raise ValueError("malformed RLP in witness node")
        return ref_off[:cnt], ref_node[:cnt]

    def new_engine(self) -> Optional["EngineCore"]:
        """Fresh native witness-engine core (None on an old library)."""
        return EngineCore(self._lib) if self.has_engine else None

    def keccak256_batch(self, payloads: Sequence[bytes]) -> List[bytes]:
        """Strictly scalar batch — the reference-equivalent baseline
        (the reference hashes one node at a time, crypto/hasher.zig:4-17)."""
        return self._batch_hash(payloads, self._lib.phant_keccak256_batch)

    def keccak256_batch_fast(self, payloads: Sequence[bytes]) -> List[bytes]:
        """The framework's own hashing path: 8-way AVX-512 multi-buffer on
        capable x86 hosts, bit-identical scalar dispatch elsewhere."""
        fn = (
            self._lib.phant_keccak256_batch_fast
            if self.has_fast_keccak
            else self._lib.phant_keccak256_batch
        )
        return self._batch_hash(payloads, fn)

    def _batch_hash(self, payloads: Sequence[bytes], fn) -> List[bytes]:
        n = len(payloads)
        if n == 0:
            return []
        blob, offsets, lens = self._layout(payloads)
        out = ctypes.create_string_buffer(32 * n)
        fn(blob, offsets, lens, n, out)
        raw = out.raw
        return [raw[32 * i : 32 * i + 32] for i in range(n)]


class EngineCore:
    """Handle to one native witness-engine core (native/engine.cc): the
    interning tables + verdict join of ops/witness_engine.WitnessEngine,
    kept in C++. The Python engine drives the scan/hash/commit/verdict
    protocol and keeps policy (hashing backend route, eviction, stats)."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        self._h = lib.phant_engine_new()
        import weakref

        # bind finalizer args by value — no ref back to self
        self._finalizer = weakref.finalize(
            self, lib.phant_engine_free, self._h
        )

    @property
    def nodes(self) -> int:
        return int(self._lib.phant_engine_nodes(self._h))

    @property
    def digests(self) -> int:
        return int(self._lib.phant_engine_digests(self._h))

    def flush(self) -> None:
        self._lib.phant_engine_flush(self._h)

    def scan(self, blob, offsets, lens):
        """(rows i64[n], novel_idx u32[n_novel], miss_count). rows[i] is a
        row id or -2-k for the k-th novel first occurrence of the batch."""
        import numpy as np

        n = len(lens)
        rows = np.empty(n, np.int64)
        novel_idx = np.empty(n, np.uint32)
        counts = np.zeros(2, np.uint64)
        rc = self._lib.phant_engine_scan(
            self._h,
            blob.ctypes.data_as(ctypes.c_void_p),
            offsets.ctypes.data_as(ctypes.c_void_p),
            lens.ctypes.data_as(ctypes.c_void_p),
            n,
            rows.ctypes.data_as(ctypes.c_void_p),
            novel_idx.ctypes.data_as(ctypes.c_void_p),
            counts.ctypes.data_as(ctypes.c_void_p),
        )
        if rc != 0:
            raise RuntimeError(f"engine scan failed ({rc})")
        return rows, novel_idx[: int(counts[1])], int(counts[0])

    def commit(self, blob, offsets, lens, rows, novel_idx, digests: bytes):
        """Insert the scanned novel nodes with their (caller-computed)
        digests; patches the negative entries of `rows` in place."""
        self._lib.phant_engine_commit(
            self._h,
            blob.ctypes.data_as(ctypes.c_void_p),
            offsets.ctypes.data_as(ctypes.c_void_p),
            lens.ctypes.data_as(ctypes.c_void_p),
            len(lens),
            rows.ctypes.data_as(ctypes.c_void_p),
            novel_idx.ctypes.data_as(ctypes.c_void_p),
            len(novel_idx),
            digests,
        )

    def verdict(self, rows, block_offs, roots: bytes):
        """(n_blocks,) bool verdicts; block b = rows[block_offs[b]:
        block_offs[b+1]], roots = concatenated 32B root digests."""
        import numpy as np

        n_blocks = len(block_offs) - 1
        ok = np.zeros(n_blocks, np.uint8)
        self._lib.phant_engine_verdict(
            self._h,
            rows.ctypes.data_as(ctypes.c_void_p),
            block_offs.ctypes.data_as(ctypes.c_void_p),
            n_blocks,
            roots,
            ok.ctypes.data_as(ctypes.c_void_p),
        )
        return ok.astype(bool)


_EXT_PATH = _BUILD_DIR / "phant_engine_ext.so"
_ext_lock = threading.Lock()
_ext_mod = None
_ext_failed = False


def load_engine_ext():
    """Build (if stale) and import the CPython extension driver for the
    witness-engine core (native/pyext.cc + engine.cc). Returns the module
    (with its `Engine` type) or None; PHANT_ENGINE_EXT=0 disables it (the
    ctypes core then serves, PHANT_ENGINE_NATIVE=0 the Python twin)."""
    global _ext_mod, _ext_failed
    # env checks FIRST: the kill switches must keep working after the
    # module has been cached in-process (the test matrix's "ctypes" run
    # relies on PHANT_ENGINE_EXT=0 actually forcing the fallback)
    if _ext_failed or os.environ.get("PHANT_NO_NATIVE"):
        return None
    if os.environ.get("PHANT_ENGINE_EXT", "1") != "1":
        return None
    if _ext_mod is not None:
        return _ext_mod
    with _ext_lock:
        if _ext_mod is not None:
            return _ext_mod
        try:
            import sysconfig

            # keccak.cc backs the engine's finish_native in-C hashing
            srcs = [
                _NATIVE_DIR / "pyext.cc",
                _NATIVE_DIR / "engine.cc",
                _NATIVE_DIR / "keccak.cc",
            ]
            _BUILD_DIR.mkdir(exist_ok=True)
            if not _EXT_PATH.exists() or any(
                s.stat().st_mtime > _EXT_PATH.stat().st_mtime for s in srcs
            ):
                cmd = [
                    "g++", "-O3", *_arch_flags(), "-std=c++20", "-shared",
                    "-fPIC", "-fno-rtti",
                    f"-I{sysconfig.get_paths()['include']}",
                    *(str(s) for s in srcs),
                    "-o", str(_EXT_PATH),
                ]
                # the one-time g++ compile runs UNDER _ext_lock on purpose:
                # concurrent first callers must wait for one build, not
                # race two compilers over the same .so path
                subprocess.run(cmd, check=True, capture_output=True)  # phantlint: disable=LOCKBLOCK — serialized one-time build
            import importlib.util
            from importlib.machinery import ExtensionFileLoader

            loader = ExtensionFileLoader("phant_engine_ext", str(_EXT_PATH))
            spec = importlib.util.spec_from_loader("phant_engine_ext", loader)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _ext_mod = mod
        except Exception:
            _ext_failed = True
            return None
    return _ext_mod


def load_native() -> Optional[NativeLib]:
    """Build (if stale) and load the native runtime; None if unavailable."""
    global _loaded, _load_failed
    if _loaded is not None:
        return _loaded
    if _load_failed or os.environ.get("PHANT_NO_NATIVE"):
        return None
    with _lock:
        if _loaded is not None:
            return _loaded
        try:
            # same contract as load_engine_ext: the (possibly seconds-long)
            # build is serialized under _lock so exactly one compile runs
            path = build_native()  # phantlint: disable=LOCKBLOCK — serialized one-time build
            _loaded = NativeLib(ctypes.CDLL(str(path)))
        except Exception:
            _load_failed = True
            return None
    return _loaded
