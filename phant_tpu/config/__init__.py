"""Chain configuration: network ids + geth-style fork-activation schedules.

Equivalent surface to the reference config layer (reference:
src/config/config.zig:8-94): a `ChainId` enum, a `ChainConfig` parsed from a
chainspec JSON (embedded mainnet/sepolia specs under `chainspecs/`, matching
reference src/chainspecs/*.json), and a pretty-table `dump()`
(reference: config.zig:67-90). Adds `fork_at()` — the fork-resolution logic
the reference leaves implicit (its EVM revision is hardcoded Shanghai with a
TODO, reference: src/blockchain/vm.zig:472).
"""

from __future__ import annotations

import enum
import json
from dataclasses import dataclass, fields as dc_fields
from importlib import resources
from pathlib import Path
from typing import Optional


class ChainId(enum.IntEnum):
    """(reference: config.zig:8-16)"""

    SpecTest = 0
    Mainnet = 1
    Goerli = 5
    Testing = 1337
    Holesky = 17000
    Kaustinen = 69420
    Sepolia = 11155111


#: Chain ids whose blobs arrive from a network the operator does not
#: control — consensus objects (the KZG trusted setup above all) must be
#: the real ceremony data there, never the forgeable dev constants that
#: serve config-less fixture chains (phant_tpu/crypto/kzg.py).
PUBLIC_CHAIN_IDS = frozenset(
    {ChainId.Mainnet, ChainId.Goerli, ChainId.Holesky, ChainId.Sepolia}
)


class UnsupportedNetwork(Exception):
    pass


class DeprecatedNetwork(Exception):
    pass


# Fork names ordered oldest -> newest; block-number-activated then
# timestamp-activated (post-merge) eras.
BLOCK_FORKS = (
    ("homestead", "homesteadBlock"),
    ("dao", "daoForkBlock"),
    ("tangerine", "eip150Block"),
    ("spurious_dragon", "eip155Block"),
    ("byzantium", "byzantiumBlock"),
    ("constantinople", "constantinopleBlock"),
    ("petersburg", "petersburgBlock"),
    ("istanbul", "istanbulBlock"),
    ("muir_glacier", "muirGlacierBlock"),
    ("berlin", "berlinBlock"),
    ("london", "londonBlock"),
    ("arrow_glacier", "arrowGlacierBlock"),
    ("gray_glacier", "grayGlacierBlock"),
)
TIME_FORKS = (
    ("shanghai", "shanghaiTime"),
    ("cancun", "cancunTime"),
    ("prague", "pragueTime"),
    ("osaka", "osakaTime"),
)


@dataclass
class ChainConfig:
    """Geth-style chainspec (reference: config.zig:18-61). Unknown JSON keys
    are ignored, exactly like the reference's ignore_unknown_fields parse."""

    ChainName: str = "mainnet"
    chainId: int = int(ChainId.Mainnet)
    homesteadBlock: Optional[int] = None
    daoForkBlock: Optional[int] = None
    eip150Block: Optional[int] = None
    eip155Block: Optional[int] = None
    byzantiumBlock: Optional[int] = None
    constantinopleBlock: Optional[int] = None
    petersburgBlock: Optional[int] = None
    istanbulBlock: Optional[int] = None
    muirGlacierBlock: Optional[int] = None
    berlinBlock: Optional[int] = None
    londonBlock: Optional[int] = None
    arrowGlacierBlock: Optional[int] = None
    grayGlacierBlock: Optional[int] = None
    terminalTotalDifficulty: Optional[int] = None
    terminalTotalDifficultyPassed: Optional[bool] = None
    shanghaiTime: Optional[int] = None
    cancunTime: Optional[int] = None
    pragueTime: Optional[int] = None
    osakaTime: Optional[int] = None
    # EIP-6110 (Prague): the beacon deposit contract whose logs become
    # deposit requests — per-network (geth chainspec field); None falls
    # back to the mainnet address
    depositContractAddress: Optional[str] = None

    # ------------------------------------------------------------------

    @classmethod
    def from_chainspec(cls, chainspec: str | bytes) -> "ChainConfig":
        """(reference: config.zig:53-61)"""
        raw = json.loads(chainspec)
        known = {f.name for f in dc_fields(cls)}
        return cls(**{k: v for k, v in raw.items() if k in known})

    @classmethod
    def from_chainspec_file(cls, path: str | Path) -> "ChainConfig":
        return cls.from_chainspec(Path(path).read_text())

    @classmethod
    def from_chain_id(cls, chain_id: int | ChainId) -> "ChainConfig":
        """(reference: config.zig:43-51)"""
        chain_id = ChainId(chain_id)
        if chain_id == ChainId.Mainnet:
            return cls.from_chainspec(_embedded_spec("mainnet.json"))
        if chain_id == ChainId.Sepolia:
            return cls.from_chainspec(_embedded_spec("sepolia.json"))
        if chain_id == ChainId.Goerli:
            raise DeprecatedNetwork("goerli is deprecated")
        raise UnsupportedNetwork(f"no embedded chainspec for {chain_id!r}")

    @classmethod
    def default(cls) -> "ChainConfig":
        return cls.from_chain_id(ChainId.Mainnet)

    # ------------------------------------------------------------------

    def fork_at(self, block_number: int, timestamp: int) -> str:
        """Newest active fork name at (block_number, timestamp). Beyond the
        reference: it hardcodes EVMC_SHANGHAI (vm.zig:472)."""
        active = "frontier"
        for name, attr in BLOCK_FORKS:
            activation = getattr(self, attr)
            if activation is not None and block_number >= activation:
                active = name
        for name, attr in TIME_FORKS:
            activation = getattr(self, attr)
            if activation is not None and timestamp >= activation:
                active = name
        return active

    def is_shanghai(self, timestamp: int) -> bool:
        return self.shanghaiTime is not None and timestamp >= self.shanghaiTime

    # ------------------------------------------------------------------

    def dump(self) -> str:
        """Box-drawing fork table (reference: config.zig:67-90)."""
        rows = [("Fork", "Block number", "Timestamp")]
        for name, attr in BLOCK_FORKS:
            v = getattr(self, attr)
            rows.append((name, str(v) if v is not None else "inactive", "na"))
        for name, attr in TIME_FORKS:
            v = getattr(self, attr)
            rows.append((name, "na", str(v) if v is not None else "inactive"))
        widths = [max(len(r[i]) for r in rows) for i in range(3)]

        def line(l, m, r):
            return l + m.join("─" * (w + 2) for w in widths) + r

        out = [line("┌", "┬", "┐")]
        for i, row in enumerate(rows):
            out.append(
                "│" + "│".join(f" {c.ljust(w)} " for c, w in zip(row, widths)) + "│"
            )
            if i == 0:
                out.append(line("├", "┼", "┤"))
        out.append(line("└", "┴", "┘"))
        return "\n".join(out)


def _embedded_spec(name: str) -> str:
    return (
        resources.files("phant_tpu.config").joinpath("chainspecs", name).read_text()
    )
