"""Transaction signing-hash construction and sender recovery.

Equivalent surface to the reference TxSigner (reference:
src/signer/signer.zig:27-188): per-type signing payloads (pre/post EIP-155
legacy, EIP-2930/1559 typed with their 0x01/0x02 prefix), v/y_parity
normalization, r/s validation, and sender = keccak(pubkey[1:])[12:].
"""

from __future__ import annotations

from typing import Optional, Tuple

from phant_tpu import rlp
from phant_tpu.crypto import secp256k1
from phant_tpu.crypto.keccak import keccak256
from phant_tpu.crypto.secp256k1 import SignatureError
from phant_tpu.types.transaction import (
    AccessListTx,
    BlobTx,
    FeeMarketTx,
    LegacyTx,
    SetCodeTx,
    Transaction,
    _encode_access_list,
)


def address_from_pubkey(pubkey65: bytes) -> bytes:
    """sender = keccak(uncompressed pubkey minus the 0x04 tag)[12:]
    (reference: src/signer/signer.zig:78)."""
    if len(pubkey65) != 65 or pubkey65[0] != 0x04:
        raise SignatureError("expected 65-byte uncompressed pubkey")
    return keccak256(pubkey65[1:])[12:]


def signing_hash(tx: Transaction, chain_id: int) -> bytes:
    """Hash the signature covers (reference: src/signer/signer.zig:81-188)."""
    if isinstance(tx, LegacyTx):
        base = [
            rlp.encode_uint(tx.nonce),
            rlp.encode_uint(tx.gas_price),
            rlp.encode_uint(tx.gas_limit),
            tx.to if tx.to is not None else b"",
            rlp.encode_uint(tx.value),
            tx.data,
        ]
        if tx.v in (27, 28):  # pre-EIP-155: six fields
            return keccak256(rlp.encode(base))
        # EIP-155: nine fields with chain_id, 0, 0
        base += [rlp.encode_uint(chain_id), b"", b""]
        return keccak256(rlp.encode(base))
    if isinstance(tx, AccessListTx):
        payload = [
            rlp.encode_uint(tx.chain_id_val),
            rlp.encode_uint(tx.nonce),
            rlp.encode_uint(tx.gas_price),
            rlp.encode_uint(tx.gas_limit),
            tx.to if tx.to is not None else b"",
            rlp.encode_uint(tx.value),
            tx.data,
            _encode_access_list(tx.access_list),
        ]
        return keccak256(b"\x01" + rlp.encode(payload))
    if isinstance(tx, FeeMarketTx):
        payload = [
            rlp.encode_uint(tx.chain_id_val),
            rlp.encode_uint(tx.nonce),
            rlp.encode_uint(tx.max_priority_fee_per_gas),
            rlp.encode_uint(tx.max_fee_per_gas),
            rlp.encode_uint(tx.gas_limit),
            tx.to if tx.to is not None else b"",
            rlp.encode_uint(tx.value),
            tx.data,
            _encode_access_list(tx.access_list),
        ]
        return keccak256(b"\x02" + rlp.encode(payload))
    if isinstance(tx, BlobTx):
        # EIP-4844: 0x03 ‖ rlp([..., max_fee_per_blob_gas, blob_hashes])
        payload = [
            rlp.encode_uint(tx.chain_id_val),
            rlp.encode_uint(tx.nonce),
            rlp.encode_uint(tx.max_priority_fee_per_gas),
            rlp.encode_uint(tx.max_fee_per_gas),
            rlp.encode_uint(tx.gas_limit),
            tx.to if tx.to is not None else b"",
            rlp.encode_uint(tx.value),
            tx.data,
            _encode_access_list(tx.access_list),
            rlp.encode_uint(tx.max_fee_per_blob_gas),
            [h for h in tx.blob_versioned_hashes],
        ]
        return keccak256(b"\x03" + rlp.encode(payload))
    if isinstance(tx, SetCodeTx):
        # EIP-7702: 0x04 ‖ rlp([..., access_list, authorization_list])
        payload = [
            rlp.encode_uint(tx.chain_id_val),
            rlp.encode_uint(tx.nonce),
            rlp.encode_uint(tx.max_priority_fee_per_gas),
            rlp.encode_uint(tx.max_fee_per_gas),
            rlp.encode_uint(tx.gas_limit),
            tx.to if tx.to is not None else b"",
            rlp.encode_uint(tx.value),
            tx.data,
            _encode_access_list(tx.access_list),
            [a.fields() for a in tx.authorization_list],
        ]
        return keccak256(b"\x04" + rlp.encode(payload))
    raise TypeError(f"unknown tx type {type(tx).__name__}")


AUTH_MAGIC = b"\x05"  # EIP-7702 authorization signing-domain separator


def authorization_signing_hash(auth) -> bytes:
    """keccak(0x05 ‖ rlp([chain_id, address, nonce])) — the message an
    EIP-7702 authority signs (EIP-7702; the MAGIC byte keeps it disjoint
    from every EIP-2718 tx type)."""
    return keccak256(
        AUTH_MAGIC
        + rlp.encode(
            [
                rlp.encode_uint(auth.chain_id),
                auth.address,
                rlp.encode_uint(auth.nonce),
            ]
        )
    )


def sign_authorization(
    chain_id: int, address: bytes, nonce: int, private_key: int
):
    """Test/tooling helper: a signed EIP-7702 authorization tuple."""
    from phant_tpu.types.transaction import Authorization

    unsigned = Authorization(
        chain_id=chain_id, address=address, nonce=nonce, y_parity=0, r=0, s=0
    )
    r, s, y_parity = secp256k1.sign(
        authorization_signing_hash(unsigned), private_key
    )
    return Authorization(
        chain_id=chain_id, address=address, nonce=nonce,
        y_parity=y_parity, r=r, s=s,
    )


def recover_authority(auth) -> Optional[bytes]:
    """The 20-byte authority that signed an EIP-7702 authorization tuple,
    or None when the signature is invalid. Validation per EIP-7702: low-s
    malleability and y_parity ∈ {0,1} (chain-id/nonce screening is the
    caller's per-tuple processing, chain.py)."""
    if auth.y_parity not in (0, 1):
        return None
    if not (0 < auth.r < secp256k1.N):
        return None
    if not (0 < auth.s <= secp256k1.N // 2):
        return None
    try:
        pub = secp256k1.recover_pubkey(
            authorization_signing_hash(auth), auth.r, auth.s, auth.y_parity
        )
    except SignatureError:
        return None
    return address_from_pubkey(pub)


def recovery_fields(tx: Transaction, chain_id: int) -> Tuple[int, int, int]:
    """(r, s, recovery_id), normalizing legacy v
    (reference: src/signer/signer.zig:45-75)."""
    if isinstance(tx, LegacyTx):
        v = tx.v
        if v in (27, 28):
            rec_id = v - 27
        else:
            derived = 35 + 2 * chain_id
            if v not in (derived, derived + 1):
                raise SignatureError(f"v {v} inconsistent with chain id {chain_id}")
            rec_id = v - derived
    else:
        if tx.y_parity not in (0, 1):
            raise SignatureError(f"bad y_parity {tx.y_parity}")
        if tx.chain_id_val != chain_id:
            raise SignatureError("tx chain id mismatch")
        rec_id = tx.y_parity
    return tx.r, tx.s, rec_id


def recover_rows_host(msgs, rs, ss, recids):
    """The host recovery route over raw signature rows: ONE fused native
    batch (recover + keccak + address in C, GIL released) when the
    toolchain is present, the scalar pure-Python path otherwise. Returns
    `(senders, backend)` with backend in ("native", "scalar"); None
    entries = unrecoverable. THE one definition shared by
    `TxSigner.recover_rows_async` and the serving sig engine's host
    route (ops/sig_engine.py), so the fallback semantics can never
    diverge from the oracle the lane is differential-tested against.
    Placeholder (invalid-signature) rows recover to garbage here; the
    caller's bad-mask discards them."""
    from phant_tpu.utils.native import load_native

    native = load_native()
    if native is not None:
        return native.ecrecover_batch(msgs, rs, ss, recids), "native"
    out = []
    for m, r, s, rid in zip(msgs, rs, ss, recids):
        try:
            pub = secp256k1.recover_pubkey(m, r, s, rid)
            out.append(address_from_pubkey(pub))
        except SignatureError:
            out.append(None)
    return out, "scalar"


class SigRows:
    """One transaction list's signature rows, built on the caller's own
    thread: per-tx `(signing_hash, r, s, recid)` plus the set of indices
    whose signatures failed static validation (`bad` — those rows carry a
    well-formed placeholder lane and their results are discarded, the
    `recover_senders_async` contract). This is the unit the serving sig
    lane merges across requests (ops/sig_engine.py): rows are pure host
    data, so K requests' rows concatenate into one device ecrecover
    dispatch with no per-request shape constraints."""

    __slots__ = ("msgs", "rs", "ss", "recids", "bad")

    def __init__(self, msgs, rs, ss, recids, bad):
        self.msgs = msgs
        self.rs = rs
        self.ss = ss
        self.recids = recids
        self.bad = bad  # frozenset of invalid-signature tx indices

    @property
    def n(self) -> int:
        return len(self.msgs)


class TxSigner:
    """Chain-id-aware sender recovery + test signing
    (reference: src/signer/signer.zig:20-79).

    `min_device_ecrecover` is the device-route batch floor, resolved ONCE
    at construction (env PHANT_TPU_MIN_ECRECOVER, default 64) — the r14
    bugfix: the old module helper re-read `os.environ` on every
    `recover_senders_async` call on the hot path. An explicit argument is
    the test/engine override and wins over the env."""

    def __init__(self, chain_id: int, min_device_ecrecover: Optional[int] = None):
        self.chain_id = chain_id
        if min_device_ecrecover is None:
            import os

            min_device_ecrecover = int(
                os.environ.get("PHANT_TPU_MIN_ECRECOVER", "64")
            )
        self._min_device = min_device_ecrecover

    def get_sender(self, tx: Transaction) -> bytes:
        r, s, rec_id = recovery_fields(tx, self.chain_id)
        secp256k1.validate_signature_fields(r, s)
        msg = signing_hash(tx, self.chain_id)
        pub = secp256k1.recover_pubkey(msg, r, s, rec_id)
        return address_from_pubkey(pub)

    def get_senders_batch(self, txs) -> list:
        """Recover every sender of a block's tx list in one batched device
        call when `--crypto_backend=tpu` and the batch is large enough to
        amortize dispatch latency, else through the fused native batch.
        Raises SignatureError if any signature is invalid — per-tx behavior
        matches `get_sender` exactly (differential-tested)."""
        out = self.recover_senders_async(txs)()
        bad = [i for i, a in enumerate(out) if a is None]
        if bad:
            raise SignatureError(f"unrecoverable signature at tx index {bad[0]}")
        return out

    def signature_rows(self, txs) -> SigRows:
        """The per-tx signature rows `(signing_hash, r, s, recid)` for a
        tx list — the host keccak-over-RLP work, shared by the local
        `recover_senders_async` path and the serving sig lane
        (ops/sig_engine.py), so the row semantics (invalid-signature
        placeholder lane included) can never diverge between them."""
        msgs, rs, ss, recids = [], [], [], []
        bad = set()
        for i, tx in enumerate(txs):
            try:
                r, s, rec_id = recovery_fields(tx, self.chain_id)
                secp256k1.validate_signature_fields(r, s)
            except SignatureError:
                bad.add(i)
                r, s, rec_id = 1, 1, 0  # placeholder lane; result discarded
                msgs.append(b"\x01" * 32)
            else:
                msgs.append(signing_hash(tx, self.chain_id))
            rs.append(r)
            ss.append(s)
            recids.append(rec_id)
        return SigRows(msgs, rs, ss, recids, frozenset(bad))

    def recover_senders_async(self, txs, force_cpu: bool = False):
        """Dispatch sender recovery and return `resolve() -> [address|None]`
        (None = invalid signature; the error is raised by whoever consumes
        the block, keeping prefetch failures attributed to the right block).

        Backend selection: the device kernel only wins when the batch
        amortizes transfer+dispatch latency, so batches below
        PHANT_TPU_MIN_ECRECOVER (default 64) take the fused native batch
        even on `--crypto_backend=tpu` — a single real block's ~8-200 txs
        must never pay tunnel RTT serially (round-2 lesson: the flag made
        replay 45x slower). Cross-block prefetch (chain.run_blocks)
        concatenates many blocks' txs to clear the floor, and the serving
        path's sig lane (ops/sig_engine.py — THE offload-gate story)
        merges CONCURRENT requests' rows to clear it under Engine API
        traffic where no single block can. `force_cpu`
        pins this call to the CPU path WITHOUT touching the process-global
        backend (the device-loss fallback must not race concurrent
        requests)."""
        if not txs:
            return lambda: []
        return self.recover_rows_async(
            self.signature_rows(txs), force_cpu=force_cpu
        )

    def recover_rows_async(self, rows: SigRows, force_cpu: bool = False):
        """`recover_senders_async` over PRE-BUILT signature rows — the
        serving sig lane's degrade path reuses the rows it already built
        instead of paying the signing-hash keccak pass twice
        (stateless.dispatch_sender_recovery). Same backend selection,
        same `resolve() -> [address|None]` contract."""
        from phant_tpu.backend import crypto_backend, jax_device_ok

        if rows.n == 0:
            return lambda: []
        tpu_ok = (
            not force_cpu and crypto_backend() == "tpu" and jax_device_ok()
        )
        use_tpu = tpu_ok and rows.n >= self._min_device
        if not use_tpu and tpu_ok:
            from phant_tpu.utils.native import load_native

            if load_native() is None:
                # no toolchain: the device kernel beats scalar Python
                # even below the floor (the floor only arbitrates
                # device vs the fused NATIVE batch)
                use_tpu = True

        msgs, rs, ss, recids, bad = rows.msgs, rows.rs, rows.ss, rows.recids, rows.bad

        if use_tpu:
            from phant_tpu.ops.secp256k1_jax import ecrecover_batch_async

            inner = ecrecover_batch_async(msgs, rs, ss, recids)
        else:
            # the shared host route: fused native batch, or scalar when
            # the toolchain is absent (recover_rows_host)
            done, _backend = recover_rows_host(msgs, rs, ss, recids)
            inner = lambda: done  # noqa: E731

        def resolve():
            out = inner()
            return [None if i in bad else a for i, a in enumerate(out)]

        return resolve

    def sign(self, tx: Transaction, private_key: int) -> Transaction:
        """Returns a copy of `tx` carrying the signature."""
        from dataclasses import replace

        msg = signing_hash(tx, self.chain_id)
        r, s, y_parity = secp256k1.sign(msg, private_key)
        if isinstance(tx, LegacyTx):
            v = 35 + 2 * self.chain_id + y_parity if tx.v not in (27, 28) else 27 + y_parity
            return replace(tx, v=v, r=r, s=s)
        return replace(tx, y_parity=y_parity, r=r, s=s)
