"""Merkle Patricia Trie: construction, root computation, node enumeration.

Equivalent surface to the reference (reference: src/mpt/mpt.zig:13-314):
`keyval` pairs -> trie -> keccak root, with hex-prefix nibble encoding and
the <32-byte node-embedding rule. Goes beyond the reference by also keeping
the built node structure around for proof generation (phant_tpu/mpt/proof.py)
and for the TPU level-order hashing pipeline (phant_tpu/ops/mpt_jax.py):
the reference computes roots only (reference: src/mpt/mpt.zig:38-45).

Yellow-paper appendix D. Node kinds: leaf, extension, branch, empty.
A node's reference inside its parent is its RLP structure itself when the
encoding is shorter than 32 bytes, else keccak256 of the encoding.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

from phant_tpu import rlp
from phant_tpu.crypto.keccak import keccak256

EMPTY_TRIE_ROOT = keccak256(rlp.encode(b""))


def bytes_to_nibbles(key: bytes) -> Tuple[int, ...]:
    out = []
    for b in key:
        out.append(b >> 4)
        out.append(b & 0x0F)
    return tuple(out)


def encode_hex_prefix(nibbles: Sequence[int], is_leaf: bool) -> bytes:
    """Hex-prefix encoding (yellow paper appendix C; reference:
    src/mpt/mpt.zig:285-314)."""
    flag = 0x20 if is_leaf else 0x00
    if len(nibbles) % 2:  # odd
        first = flag | 0x10 | nibbles[0]
        rest = nibbles[1:]
    else:
        first = flag
        rest = nibbles
    out = bytearray([first])
    for i in range(0, len(rest), 2):
        out.append((rest[i] << 4) | rest[i + 1])
    return bytes(out)


def decode_hex_prefix(data: bytes) -> Tuple[Tuple[int, ...], bool]:
    if not data:
        raise ValueError("empty hex-prefix encoding")
    flag = data[0]
    is_leaf = bool(flag & 0x20)
    nibbles: List[int] = []
    if flag & 0x10:  # odd
        nibbles.append(flag & 0x0F)
    for b in data[1:]:
        nibbles.append(b >> 4)
        nibbles.append(b & 0x0F)
    return tuple(nibbles), is_leaf


# --- trie nodes -----------------------------------------------------------


@dataclass
class LeafNode:
    path: Tuple[int, ...]
    value: bytes


@dataclass
class ExtensionNode:
    path: Tuple[int, ...]
    child: "Node"


@dataclass
class BranchNode:
    children: List[Optional["Node"]] = field(default_factory=lambda: [None] * 16)
    value: Optional[bytes] = None


Node = Union[LeafNode, ExtensionNode, BranchNode]


def _common_prefix_len(a: Sequence[int], b: Sequence[int]) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


def _noop_evict(node: Node) -> None:
    pass


def _insert(
    node: Optional[Node], path: Tuple[int, ...], value: bytes, evict=_noop_evict
) -> Node:
    """Insert (path, value); mirrors the reference's recursive insertNode
    (reference: src/mpt/mpt.zig:47-119) but returns fresh subtree roots.

    `evict(node)` is called for every node whose cached encoding becomes
    stale — both MUTATED nodes (their encoding changes) and DISCARDED nodes
    (their id may be reused by a new object, so a live cache entry would be
    a use-after-free-style stale hit). Untouched subtrees keep their cache
    entries, making repeated root computation O(dirty-paths), not O(trie).
    """
    if node is None:
        return LeafNode(path, value)

    if isinstance(node, LeafNode):
        if node.path == path:
            evict(node)  # mutated
            node.value = value
            return node
        common = _common_prefix_len(node.path, path)
        branch = BranchNode()
        old_rest, new_rest = node.path[common:], path[common:]
        evict(node)  # discarded (replaced by the split structure)
        if not old_rest:
            branch.value = node.value
        else:
            branch.children[old_rest[0]] = LeafNode(old_rest[1:], node.value)
        if not new_rest:
            branch.value = value
        else:
            branch.children[new_rest[0]] = LeafNode(new_rest[1:], value)
        if common:
            return ExtensionNode(node.path[:common], branch)
        return branch

    if isinstance(node, ExtensionNode):
        common = _common_prefix_len(node.path, path)
        if common == len(node.path):
            evict(node)  # child set below: encoding changes
            node.child = _insert(node.child, path[common:], value, evict)
            return node
        # split the extension
        evict(node)  # discarded
        branch = BranchNode()
        ext_rest = node.path[common:]
        # the shortened old subtree hangs under ext_rest[0]
        if len(ext_rest) == 1:
            branch.children[ext_rest[0]] = node.child
        else:
            branch.children[ext_rest[0]] = ExtensionNode(ext_rest[1:], node.child)
        new_rest = path[common:]
        if not new_rest:
            branch.value = value
        else:
            branch.children[new_rest[0]] = LeafNode(new_rest[1:], value)
        if common:
            return ExtensionNode(path[:common], branch)
        return branch

    # BranchNode
    evict(node)  # value or child slot changes either way
    if not path:
        node.value = value
        return node
    node.children[path[0]] = _insert(node.children[path[0]], path[1:], value, evict)
    return node


# --- deletion (yellow-paper node collapse) ---------------------------------
#
# The reference is insert-only (reference: src/mpt/mpt.zig:47-119 has no
# delete); deletion is required here because the stateless product path
# must handle EIP-158 account cleanup, selfdestruct, and storage-zeroing —
# all of which REMOVE keys and collapse branch/extension structure.


class _Unresolved(Exception):
    """Raised when a collapse needs the structure of an opaque child (only
    possible on PartialTrie, where unwitnessed subtrees are HashNodes)."""


def _merge_into(nibble_prefix: Tuple[int, ...], child: Node, evict=_noop_evict) -> Node:
    """Prepend `nibble_prefix` to a child that lost its parent branch/ext."""
    if isinstance(child, LeafNode):
        evict(child)  # discarded: replaced by the merged leaf
        return LeafNode(nibble_prefix + child.path, child.value)
    if isinstance(child, ExtensionNode):
        evict(child)  # discarded: replaced by the merged extension
        return ExtensionNode(nibble_prefix + child.path, child.child)
    if isinstance(child, BranchNode):
        if not nibble_prefix:
            return child
        return ExtensionNode(nibble_prefix, child)
    # HashNode (PartialTrie): its kind is unknown, so the merged node's
    # encoding cannot be computed — the witness is insufficient
    raise _Unresolved()


def _collapse_branch(node: BranchNode, evict=_noop_evict) -> Optional[Node]:
    """Re-normalize a branch after a child was deleted."""
    live = [(i, c) for i, c in enumerate(node.children) if c is not None]
    if node.value is not None:
        if not live:
            evict(node)  # discarded
            return LeafNode((), node.value)
        return node
    if not live:
        return None
    if len(live) == 1:
        i, child = live[0]
        evict(node)  # discarded (folded into the merged child)
        return _merge_into((i,), child, evict)
    return node


def _delete(
    node: Optional[Node], path: Tuple[int, ...], evict=_noop_evict
) -> Optional[Node]:
    """Remove `path`; returns the re-normalized subtree (None = empty).
    Missing keys are a no-op (matching geth's trie delete semantics).
    `evict` receives every node whose cached encoding goes stale (mutated
    ancestors and discarded/collapsed nodes) — see _insert."""
    if node is None:
        return None

    if isinstance(node, LeafNode):
        if node.path == tuple(path):
            evict(node)  # discarded
            return None
        return node

    if not isinstance(node, (ExtensionNode, BranchNode)):
        # opaque HashNode (PartialTrie): the delete path crosses an
        # unwitnessed subtree
        raise _Unresolved()

    if isinstance(node, ExtensionNode):
        n = len(node.path)
        if tuple(path[:n]) != node.path:
            return node  # key absent
        # anything below may mutate in place; this encoding goes stale
        # either way (eviction on a no-op absent-key delete is harmless)
        evict(node)
        new_child = _delete(node.child, tuple(path[n:]), evict)
        if new_child is node.child:
            return node  # absent below or mutated in place
        if new_child is None:
            evict(node)  # discarded
            return None
        return _merge_into(node.path, new_child, evict)

    # BranchNode
    if not path:
        if node.value is None:
            return node  # key absent
        evict(node)
        node.value = None
        return _collapse_branch(node, evict)
    i = path[0]
    old_child = node.children[i]
    if old_child is None:
        return node  # key absent
    evict(node)  # see extension case: stale either way
    new_child = _delete(old_child, tuple(path[1:]), evict)
    if new_child is old_child:
        return node  # absent below or mutated in place
    node.children[i] = new_child
    if new_child is not None:
        return node
    return _collapse_branch(node, evict)


class Trie:
    """A build-once/query MPT over byte keys.

    The STRUCTURAL algorithms (insert / delete / branch collapse /
    extension merge) are radix-generic: nothing in them assumes 16-way
    branching beyond `children[digit]` indexing. Commitment-scheme
    plugins (phant_tpu/commitment/) subclass with a different digit
    alphabet and node codec — `_digits` maps a key to its path digits
    (nibbles here; bits for the binary scheme) and `_path_enc` encodes a
    leaf/extension path (hex-prefix here; bit-prefix for binary). Both
    hooks default to the hexary-MPT behavior, byte-identical to the
    pre-plugin code."""

    #: key -> path digits (hexary: nibbles; binary scheme: bits)
    _digits = staticmethod(bytes_to_nibbles)
    #: leaf/extension path encoding (hexary: yellow-paper hex-prefix)
    _path_enc = staticmethod(encode_hex_prefix)

    def __init__(self):
        self.root: Optional[Node] = None
        # upper bound on leaf count (overwrites double-count); used only as
        # the device-dispatch size heuristic in trie_root_hash
        self.approx_size = 0
        # node-id -> (structure, encoding) memo with PER-PATH invalidation:
        # put/delete evict exactly the mutated/discarded nodes (and any
        # freed object is evicted before its id can be reused), so repeated
        # roots after K updates re-encode only the K dirty paths.
        self._enc_cache: Dict[int, Tuple[rlp.RLPItem, bytes]] = {}
        # mutation epoch: bumped on every put/delete; the device HashPlan
        # cache (phant_tpu/ops/mpt_jax.py trie_root_device) is keyed on it
        self._epoch = 0

    def _evict(self, node: Node) -> None:
        self._enc_cache.pop(id(node), None)

    def put(self, key: bytes, value: bytes) -> None:
        if not value:  # empty value = delete (geth trie semantics)
            self.delete(key)
            return
        self._epoch += 1
        self.approx_size += 1
        # per-path cache eviction: untouched subtrees keep their encodings,
        # so a root after K updates re-encodes O(K * depth) nodes only
        self.root = _insert(self.root, self._digits(key), value, self._evict)

    def delete(self, key: bytes) -> None:
        """Remove `key` with full branch-collapse/extension-merge
        re-normalization (no-op when absent)."""
        self._epoch += 1
        self.approx_size = max(self.approx_size - 1, 0)
        self.root = _delete(self.root, self._digits(key), self._evict)

    def get(self, key: bytes) -> Optional[bytes]:
        node, path = self.root, self._digits(key)
        while node is not None:
            if isinstance(node, LeafNode):
                return node.value if node.path == tuple(path) else None
            if isinstance(node, ExtensionNode):
                n = len(node.path)
                if tuple(path[:n]) != node.path:
                    return None
                node, path = node.child, path[n:]
                continue
            if not path:
                return node.value
            node, path = node.children[path[0]], path[1:]
        return None

    # --- encoding ---------------------------------------------------------

    def node_encoding(self, node: Node) -> Tuple[rlp.RLPItem, bytes]:
        """(structure, rlp_encoding) of a node, memoized per build epoch —
        proof generation and root hashing share subtree encodings instead of
        re-walking them."""
        cached = self._enc_cache.get(id(node))
        if cached is not None:
            return cached
        if isinstance(node, LeafNode):
            structure: rlp.RLPItem = [self._path_enc(node.path, True), node.value]
        elif isinstance(node, ExtensionNode):
            structure = [self._path_enc(node.path, False), self._ref(node.child)]
        else:
            slots: List[rlp.RLPItem] = []
            for child in node.children:
                slots.append(b"" if child is None else self._ref(child))
            slots.append(node.value if node.value is not None else b"")
            structure = slots
        encoded = rlp.encode(structure)
        result = (structure, encoded)
        self._enc_cache[id(node)] = result
        return result

    def node_structure(self, node: Node) -> rlp.RLPItem:
        """The node's RLP structure (list), before the embed-or-hash rule."""
        return self.node_encoding(node)[0]

    def _ref(self, node: Node) -> rlp.RLPItem:
        """Reference to a child: embedded structure if rlp < 32B, else hash
        (reference: src/mpt/mpt.zig:132-281 node encode paths)."""
        structure, encoded = self.node_encoding(node)
        if len(encoded) < 32:
            return structure
        return keccak256(encoded)

    def root_hash(self) -> bytes:
        if self.root is None:
            return EMPTY_TRIE_ROOT
        return keccak256(self.node_encoding(self.root)[1])


# --- public API mirroring the reference ----------------------------------


def trie_root_hash(trie: Trie) -> bytes:
    """Root of a built trie through the selected crypto backend: device
    level-order hashing on `--crypto_backend=tpu` (phant_tpu/ops/mpt_jax.py,
    with automatic host fallback for embedded-node tries), host recursion
    otherwise. This is the root used by the block path
    (phant_tpu/blockchain/chain.py) and the state root (phant_tpu/state/root.py).

    Tiny tries (a handful of txs/receipts) stay on the host even on the tpu
    backend: per-level dispatch latency would dwarf the hashing. The
    threshold is leaf-count based (PHANT_TPU_MIN_TRIE, default 192) on top
    of THE offload-gate story (ops/root_engine.py module docstring — the
    single source of truth for when plan bytes beat the native hasher)."""
    from phant_tpu.backend import crypto_backend, jax_device_ok

    if (
        crypto_backend() == "tpu"
        and trie.approx_size >= _min_device_trie()
        and jax_device_ok()
        and _device_root_pays(trie)
    ):
        from phant_tpu.ops.mpt_jax import trie_root_device

        return trie_root_device(trie)
    return trie.root_hash()


def _min_device_trie() -> int:
    import os

    return int(os.environ.get("PHANT_TPU_MIN_TRIE", "192"))


def _device_root_pays(trie: Trie) -> bool:
    """Link-aware offload gate for device trie roots (THE offload-gate
    story lives in ops/root_engine.py; this applies it with a ~600B/leaf
    payload estimate — leaf + amortized branch encodings — through the
    shared cost model, phant_tpu/backend.py device_offload_pays)."""
    import os

    if os.environ.get("PHANT_TPU_FORCE_TRIE", "0") not in ("", "0"):
        return True
    from phant_tpu.backend import device_offload_pays

    return device_offload_pays(trie.approx_size * 600)


def trie_root(pairs: Iterable[Tuple[bytes, bytes]]) -> bytes:
    """Root of the trie mapping key bytes -> value bytes (values already RLP).

    Equivalent of the reference's `mptize` over KeyVals
    (reference: src/mpt/mpt.zig:38-45)."""
    trie = Trie()
    for key, value in pairs:
        trie.put(key, value)
    return trie_root_hash(trie)


def ordered_trie_root(values: Sequence[bytes]) -> bytes:
    """Root of the index-keyed trie used for tx/receipt/withdrawal roots:
    key i = rlp(i) (reference: src/engine_api/execution_payload.zig:128-139,
    src/blockchain/blockchain.zig:209-235)."""
    return trie_root((rlp.encode(rlp.encode_uint(i)), v) for i, v in enumerate(values))


def secure_trie_root(pairs: Iterable[Tuple[bytes, bytes]]) -> bytes:
    """Root with keccak-hashed keys — the account/storage trie form. The
    reference never builds this (state-root check is TODO-disabled,
    reference: src/blockchain/blockchain.zig:83-85); the north star needs it."""
    return trie_root((keccak256(k), v) for k, v in pairs)
