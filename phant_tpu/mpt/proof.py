"""MPT proofs and stateless witness verification.

Beyond-reference functionality (the reference computes roots only,
reference: src/mpt/mpt.zig:38-45): generate eth_getProof-style proofs from a
built trie, and verify key/value pairs against a root from a bag of nodes —
the CPU oracle for the batched TPU witness-verification pipeline
(BASELINE.md config #3).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from phant_tpu import rlp
from phant_tpu.crypto.keccak import keccak256
from phant_tpu.mpt.mpt import (
    ExtensionNode,
    LeafNode,
    Node,
    Trie,
    bytes_to_nibbles,
    decode_hex_prefix,
    EMPTY_TRIE_ROOT,
)


class ProofError(ValueError):
    """Raised when a proof is malformed or inconsistent with the root."""


def generate_proof(trie: Trie, key: bytes) -> List[bytes]:
    """RLP encodings of every hash-referenced node on the path to `key`
    (embedded <32B nodes travel inside their parent, as in eth_getProof)."""
    proof: List[bytes] = []
    node = trie.root
    if node is None:
        return proof
    path = bytes_to_nibbles(key)

    def emit(n: Node) -> None:
        encoded = trie.node_encoding(n)[1]
        if len(encoded) >= 32 or n is trie.root:
            proof.append(encoded)

    while node is not None:
        emit(node)
        if isinstance(node, LeafNode):
            return proof
        if isinstance(node, ExtensionNode):
            n = len(node.path)
            if tuple(path[:n]) != node.path:
                return proof
            path = path[n:]
            child = node.child
        else:
            if not path:
                return proof
            child = node.children[path[0]]
            path = path[1:]
        # embedded (<32B) children travel inside the parent encoding; `emit`
        # filters them out while the walk continues through them.
        node = child
    return proof


def _node_db(proof_nodes: Iterable[bytes]) -> Dict[bytes, bytes]:
    return {keccak256(n): n for n in proof_nodes}


def verify_proof(
    root: bytes,
    key: bytes,
    proof_nodes: Sequence[bytes] = (),
    node_db: Optional[Dict[bytes, bytes]] = None,
) -> Optional[bytes]:
    """Walk `key` from `root` through `proof_nodes`; returns the value, or
    None for a valid absence proof. Raises ProofError on inconsistency.
    Pass a prebuilt `node_db` (from :func:`_node_db`) to amortize hashing
    across many keys."""
    if root == EMPTY_TRIE_ROOT:
        if node_db is None and list(proof_nodes):
            raise ProofError("nonempty proof for empty root")
        return None
    db = node_db if node_db is not None else _node_db(proof_nodes)
    path = list(bytes_to_nibbles(key))

    def resolve(ref) -> rlp.RLPItem:
        if isinstance(ref, list):  # embedded node structure
            return ref
        ref = bytes(ref)
        if len(ref) != 32:
            raise ProofError(f"bad node reference length {len(ref)}")
        enc = db.get(ref)
        if enc is None:
            raise ProofError("missing proof node")
        return rlp.decode(enc)

    item: rlp.RLPItem = resolve(root)
    while True:
        if not isinstance(item, list):
            raise ProofError("node is not a list")
        if len(item) == 17:  # branch
            if not path:
                value = bytes(item[16])
                return value or None
            ref = item[path[0]]
            if ref == b"" or ref == []:
                return None  # absence
            path = path[1:]
            item = resolve(ref)
            continue
        if len(item) == 2:
            nibbles, is_leaf = decode_hex_prefix(bytes(item[0]))
            if is_leaf:
                if tuple(path) == nibbles:
                    return bytes(item[1])
                return None  # absence (diverging leaf)
            n = len(nibbles)
            if tuple(path[:n]) != nibbles:
                return None  # absence (diverging extension)
            path = path[n:]
            item = resolve(item[1])
            continue
        raise ProofError(f"node with {len(item)} items")


def verify_witness(
    root: bytes,
    entries: Sequence[Tuple[bytes, Optional[bytes]]],
    proof_nodes: Sequence[bytes],
) -> bool:
    """Multiproof/witness check: every (key, expected_value_or_None) must
    verify against `root` using the shared node bag (hashed once)."""
    db = _node_db(proof_nodes)
    for key, expected in entries:
        got = verify_proof(root, key, node_db=db)
        if got != expected:
            return False
    return True


def _child_refs(item: rlp.RLPItem) -> List[bytes]:
    """32-byte child hash references of a decoded trie node, recursing into
    embedded (<32B) children. Leaf and branch VALUES are not references."""
    refs: List[bytes] = []
    if not isinstance(item, list):
        return refs
    if len(item) == 17:
        for child in item[:16]:
            if isinstance(child, list):
                refs.extend(_child_refs(child))
            elif len(child) == 32:
                refs.append(bytes(child))
    elif len(item) == 2:
        first = bytes(item[0])
        if first and not (first[0] & 0x20):  # extension
            child = item[1]
            if isinstance(child, list):
                refs.extend(_child_refs(child))
            elif len(child) == 32:
                refs.append(bytes(child))
        elif first and not isinstance(item[1], list):
            # leaf: an account-shaped value (4-string list, 32-byte items 2
            # and 3) commits its storage root — storage-trie witness nodes
            # link through it (mirrors the native/device scanners)
            try:
                body = rlp.decode(bytes(item[1]))
            except Exception:
                return refs
            if (
                isinstance(body, list)
                and len(body) == 4
                and all(not isinstance(x, list) for x in body)
                and len(body[2]) == 32
                and len(body[3]) == 32
            ):
                refs.append(bytes(body[2]))
    return refs


def verify_witness_linked(root: bytes, proof_nodes: Sequence[bytes]) -> bool:
    """Full structural witness check on host: the nodes must form a connected
    subtree rooted at `root` — every node reachable from the root via hash
    references (BFS through the node bag). This is the CPU baseline of the
    device linkage verdict (phant_tpu/ops/witness_jax.py
    witness_verify_linked); both reject a witness whose parent->child hash
    chain is broken, not just one whose root is absent."""
    if root == EMPTY_TRIE_ROOT:
        return not list(proof_nodes)
    db = _node_db(proof_nodes)
    if root not in db:
        return False
    seen = {root}
    frontier = [root]
    while frontier:
        nxt: List[bytes] = []
        for digest in frontier:
            enc = db.get(digest)
            if enc is None:
                continue  # child outside the witness: allowed (not proven)
            for ref in _child_refs(rlp.decode(enc)):
                if ref in db and ref not in seen:
                    seen.add(ref)
                    nxt.append(ref)
        frontier = nxt
    return len(seen) == len(db)
