"""Persistent XLA compilation cache for the device kernels.

The heavy kernels (the 256-step ecrecover ladder in particular) take
minutes to compile but milliseconds to run; caching compiled programs
under build/jax_cache makes every process after the first start instantly.
Opt out with PHANT_NO_JAX_CACHE=1.
"""

from __future__ import annotations

import os
from pathlib import Path

_configured = False


def enable_compilation_cache() -> None:
    global _configured
    if _configured or os.environ.get("PHANT_NO_JAX_CACHE"):
        return
    _configured = True
    try:
        import jax

        default = Path(__file__).resolve().parents[2] / "build" / "jax_cache"
        cache_dir = os.environ.get("PHANT_JAX_CACHE", str(default))
        Path(cache_dir).mkdir(parents=True, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass  # older jax or read-only fs: kernels still work, just uncached
