"""Persistent XLA compilation cache for the device kernels.

The heavy kernels (the ecrecover ladders in particular) take minutes to
compile but milliseconds to run; caching compiled programs under
build/jax_cache makes every process after the first start instantly.

jax SEGFAULTS — not raises — reading or writing a cache entry corrupted
by concurrent writers, so every process class gets a SINGLE-WRITER dir:
tests use a per-session tmpdir (tests/conftest.py), bench-contract
subprocesses get per-test dirs, the driver dryrun uses
build/jax_cache_dryrun, and only the bench/serving CLI use the shared
build/jax_cache default. Point elsewhere with PHANT_JAX_CACHE; opt out
entirely with PHANT_NO_COMPILE_CACHE=1 (PHANT_NO_JAX_CACHE is a legacy
alias).
"""

from __future__ import annotations

import os
import threading
from pathlib import Path

_configured = False
_configure_lock = threading.Lock()


def enable_compilation_cache() -> None:
    global _configured
    if (
        _configured
        or os.environ.get("PHANT_NO_JAX_CACHE", "0") not in ("", "0")
        or os.environ.get("PHANT_NO_COMPILE_CACHE", "0") not in ("", "0")
    ):
        return
    # lock-serialized (phantlint LOCK): concurrent first-use from two
    # request threads must not interleave the three jax.config.update
    # calls (the config object is process-global)
    with _configure_lock:
        if _configured:
            return
        _configured = True
        try:
            import jax

            default = Path(__file__).resolve().parents[2] / "build" / "jax_cache"
            cache_dir = os.environ.get("PHANT_JAX_CACHE", str(default))
            Path(cache_dir).mkdir(parents=True, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        except Exception:
            pass  # older jax or read-only fs: still works, just uncached
