"""Batched secp256k1 ecrecover on TPU via JAX.

The reference recovers one sender at a time through C libsecp256k1
(reference: src/crypto/ecdsa.zig:19-26, called per-tx from
src/signer/signer.zig:40-79). Here the whole recovery — point
decompression, r^-1 mod n, the double-scalar multiplication
Q = u1*G + u2*R (Shamir's trick), Jacobian->affine conversion, and
keccak256(pubkey) -> address — runs on device for a whole batch of
signatures at once (BASELINE.md config #4).

TPU-first design notes:
- u256 values are 16 x 16-bit limbs in uint32 lanes (a 16x16 product fits
  uint32; column sums stay < 2^21, so schoolbook multiply needs no u64).
- Reductions mod p and mod n use the "fold" identity 2^256 ≡ K (mod m)
  for m = 2^256 - K; both moduli are folds + one conditional subtract.
- Modular inverse / square root are fixed-exponent square-and-multiply
  `lax.scan`s over precomputed exponent bits (p-2, (p+1)/4, n-2).
- The 256-step Shamir ladder is a `lax.scan` whose body is one Jacobian
  double + one mixed add + one exceptional double, all branch-free via
  lane selects (identity tracked as Z == 0).
- Everything is fixed-shape; `recovery_id >= 2` (x = r + n, never emitted
  by Ethereum signers) falls back to the CPU backend.

Differential-tested bit-exactly against phant_tpu/crypto/secp256k1.py.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from phant_tpu.crypto.secp256k1 import GX, GY, N, P

LIMBS = 16  # 16-bit limbs per u256
MASK16 = np.uint32(0xFFFF)

K_P = 2**256 - P  # 2^32 + 977
K_N = 2**256 - N


def _int_to_limbs_np(x: int, width: int = LIMBS) -> np.ndarray:
    return np.array([(x >> (16 * j)) & 0xFFFF for j in range(width)], dtype=np.uint32)


def _const_width(x: int) -> int:
    w = 1
    while x >> (16 * w):
        w += 1
    return w


def _bits_msb(x: int, nbits: int = 256) -> np.ndarray:
    return np.array([(x >> (nbits - 1 - i)) & 1 for i in range(nbits)], dtype=np.uint32)


class _ModSpec:
    """Modulus m = 2^256 - K with precomputed fold constant + limb forms."""

    def __init__(self, m: int, folds: int):
        self.m = m
        self.k = 2**256 - m
        self.k_limbs = _int_to_limbs_np(self.k, _const_width(self.k))
        self.m17 = _int_to_limbs_np(m, 17)
        self.folds = folds


P_SPEC = _ModSpec(P, folds=3)  # K_P < 2^33: 3 folds reach < 2m
N_SPEC = _ModSpec(N, folds=4)  # K_N < 2^129: 4 folds reach < 2m

_EXP_P_MINUS_2 = _bits_msb(P - 2)
_EXP_SQRT = _bits_msb((P + 1) // 4)
_EXP_N_MINUS_2 = _bits_msb(N - 2)

_G_X = _int_to_limbs_np(GX)
_G_Y = _int_to_limbs_np(GY)
# 2G, precomputed host-side for the (cryptographically improbable) R == G
# exceptional case of the one-off G+R affine add
_G2 = None  # filled below once CPU helpers are importable


def _cpu_g2() -> Tuple[np.ndarray, np.ndarray]:
    global _G2
    if _G2 is None:
        from phant_tpu.crypto.secp256k1 import _point_add

        g2 = _point_add((GX, GY), (GX, GY))
        # idempotent pure precompute: racing writers store identical
        # tuples, and this runs at jit-trace time where a lock would
        # serialize tracing for no benefit
        _G2 = (_int_to_limbs_np(g2[0]), _int_to_limbs_np(g2[1]))  # phantlint: disable=LOCK — benign double-compute of a constant
    return _G2


# ---------------------------------------------------------------------------
# limb arithmetic (all shapes (B, w) uint32 with limbs < 2^16)
# ---------------------------------------------------------------------------


def _carry_unrolled(cols: jnp.ndarray, width: int) -> jnp.ndarray:
    """Propagate carries over `width` columns (statically unrolled so the
    whole thing fuses into one elementwise program; column values must stay
    < 2^31 so `col + carry` cannot overflow uint32)."""
    out = []
    carry = jnp.zeros(cols.shape[:-1], jnp.uint32)
    for i in range(width):
        t = cols[..., i] + carry
        out.append(t & MASK16)
        carry = t >> 16
    return jnp.stack(out, axis=-1), carry


def _pad_cols(x: jnp.ndarray, left: int, width: int) -> jnp.ndarray:
    """Place x's columns at offset `left` in a width-`width` row (static
    shift = concatenation, an elementwise-fusable op — never a scatter)."""
    right = width - left - x.shape[-1]
    pad = [(0, 0)] * (x.ndim - 1) + [(left, right)]
    return jnp.pad(x, pad)


def _mul_wide(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(B,16) x (B,16) -> (B,32) full 512-bit product.

    Schoolbook columns are accumulated with STATIC-shift pads + adds
    instead of `.at[].add` scatters: XLA lowers scatters to slow serialized
    updates on TPU, while pad+add fuses into the elementwise graph. Column
    sums stay < 2^21 (16 lo + 16 hi contributions of < 2^16), so uint32
    accumulation is exact."""
    cols = jnp.zeros(a.shape[:-1] + (33,), jnp.uint32)
    for i in range(LIMBS):
        prod = a[..., i : i + 1] * b  # < 2^32, exact in uint32
        cols = cols + _pad_cols(prod & MASK16, i, 33)
        cols = cols + _pad_cols(prod >> 16, i + 1, 33)
    limbs, carry = _carry_unrolled(cols, 32)
    return limbs  # product < 2^512 so the final carry is 0


def _mul_const(h: jnp.ndarray, k_limbs: np.ndarray) -> jnp.ndarray:
    """(B,w) * constant (k,) -> (B, w+k) exact product (pad+add columns,
    same rationale as _mul_wide)."""
    w = h.shape[-1]
    k = len(k_limbs)
    kk = jnp.asarray(k_limbs)
    width = w + k + 1
    cols = jnp.zeros(h.shape[:-1] + (width,), jnp.uint32)
    for i in range(w):
        prod = h[..., i : i + 1] * kk
        cols = cols + _pad_cols(prod & MASK16, i, width)
        cols = cols + _pad_cols(prod >> 16, i + 1, width)
    limbs, _ = _carry_unrolled(cols, w + k)
    return limbs


def _add_wide(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(B,wa) + (B,wb) -> (B, max+1)."""
    w = max(a.shape[-1], b.shape[-1])
    pa = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, w - a.shape[-1])])
    pb = jnp.pad(b, [(0, 0)] * (b.ndim - 1) + [(0, w - b.shape[-1])])
    limbs, carry = _carry_unrolled(pa + pb, w)
    return jnp.concatenate([limbs, carry[..., None]], axis=-1)


def _sub_borrow(a: jnp.ndarray, b: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """a - b limbwise; returns (difference, borrowed) with equal widths."""
    w = a.shape[-1]
    ai = a.astype(jnp.int32)
    bi = b.astype(jnp.int32)
    out = []
    borrow = jnp.zeros(a.shape[:-1], jnp.int32)
    for i in range(w):
        t = ai[..., i] - bi[..., i] - borrow
        out.append((t & 0xFFFF).astype(jnp.uint32))
        borrow = (t < 0).astype(jnp.int32)
    return jnp.stack(out, axis=-1), borrow > 0


def _cond_sub(a: jnp.ndarray, m_limbs: np.ndarray) -> jnp.ndarray:
    """a mod-subtract the constant m once if a >= m (same width)."""
    m = jnp.asarray(m_limbs)
    m = jnp.broadcast_to(m, a.shape)
    d, borrowed = _sub_borrow(a, m)
    return jnp.where(borrowed[..., None], a, d)


def _fold(x: jnp.ndarray, spec: _ModSpec) -> jnp.ndarray:
    """Reduce a wide value to (B,16) using 2^256 ≡ K (mod m)."""
    for _ in range(spec.folds):
        if x.shape[-1] <= LIMBS:
            break
        lo = x[..., :LIMBS]
        hi = x[..., LIMBS:]
        x = _add_wide(lo, _mul_const(hi, spec.k_limbs))
    # width is now <= 17 and value < 2m
    w = x.shape[-1]
    if w < 17:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, 17 - w)])
    x = _cond_sub(x[..., :17], spec.m17)
    return x[..., :LIMBS]


def _mul_mod(a, b, spec: _ModSpec):
    return _fold(_mul_wide(a, b), spec)


def _add_mod(a, b, spec: _ModSpec):
    return _fold(_add_wide(a, b), spec)


def _sub_mod(a, b, spec: _ModSpec):
    d, borrowed = _sub_borrow(a, b)
    m = jnp.broadcast_to(jnp.asarray(_int_to_limbs_np(spec.m)), d.shape)
    limbs, _ = _carry_unrolled(d + m, LIMBS)
    return jnp.where(borrowed[..., None], limbs, d)


def _is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == 0, axis=-1)


def _eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == b, axis=-1)


def _lt_const(a: jnp.ndarray, m: int) -> jnp.ndarray:
    """a < m (for range checks against n)."""
    _, borrowed = _sub_borrow(a, jnp.broadcast_to(jnp.asarray(_int_to_limbs_np(m)), a.shape))
    return borrowed


def _pow_fixed(base: jnp.ndarray, exp_bits: np.ndarray, spec: _ModSpec) -> jnp.ndarray:
    """base^e for a fixed public exponent, square-and-multiply lax.scan."""
    base = jnp.asarray(base)
    # derive the initial accumulator from the input so it inherits the
    # input's varying manual axes under shard_map (a fresh constant would be
    # replicated and break the scan carry typing)
    acc0 = (base ^ base).at[..., 0].set(1)

    def body(acc, bit):
        acc = _mul_mod(acc, acc, spec)
        with_mul = _mul_mod(acc, base, spec)
        return jnp.where(bit.astype(bool), with_mul, acc), None

    acc, _ = jax.lax.scan(body, acc0, jnp.asarray(exp_bits))
    return acc


# ---------------------------------------------------------------------------
# point arithmetic (Jacobian; identity is Z == 0)
#
# Independent field multiplications are stacked along the batch axis into a
# single wider multiply (`_mul_many`) — same FLOPs, ~3x fewer HLO ops, which
# cuts XLA compile time of the 256-step ladder dramatically.
# ---------------------------------------------------------------------------


def _mul_many(pairs, spec: _ModSpec):
    """[(a1,b1),(a2,b2),...] -> [a1*b1, a2*b2, ...] via one stacked multiply."""
    if len(pairs) == 1:
        return [_mul_mod(pairs[0][0], pairs[0][1], spec)]
    a = jnp.concatenate([p[0] for p in pairs], axis=0)
    b = jnp.concatenate([p[1] for p in pairs], axis=0)
    out = _mul_mod(a, b, spec)
    B = pairs[0][0].shape[0]
    return [out[i * B : (i + 1) * B] for i in range(len(pairs))]


def _dbl2(A, YZ, C, XB2, F):
    """Assemble the doubling result from its precomputed products."""
    D = _sub_mod(_sub_mod(XB2, A, P_SPEC), C, P_SPEC)
    D = _add_mod(D, D, P_SPEC)  # 2((X+B)^2 - A - C)
    X3 = _sub_mod(_sub_mod(F, D, P_SPEC), D, P_SPEC)
    C8 = _add_mod(C, C, P_SPEC)
    C8 = _add_mod(C8, C8, P_SPEC)
    C8 = _add_mod(C8, C8, P_SPEC)
    Z3 = _add_mod(YZ, YZ, P_SPEC)
    return D, X3, C8, Z3


def _pt_dbl(X, Y, Z):
    """Jacobian doubling for y^2 = x^3 + 7 (a = 0); 7 muls in 3 stacked
    calls. Maps identity (Z=0) to identity and (x,0) to identity (Z'=2YZ)."""
    A, Bv, YZ = _mul_many([(X, X), (Y, Y), (Y, Z)], P_SPEC)
    XB = _add_mod(X, Bv, P_SPEC)
    E = _add_mod(_add_mod(A, A, P_SPEC), A, P_SPEC)  # 3A
    C, XB2, F = _mul_many([(Bv, Bv), (XB, XB), (E, E)], P_SPEC)
    D, X3, C8, Z3 = _dbl2(A, YZ, C, XB2, F)
    (EDX3,) = _mul_many([(E, _sub_mod(D, X3, P_SPEC))], P_SPEC)
    Y3 = _sub_mod(EDX3, C8, P_SPEC)
    return X3, Y3, Z3


def _select_pt(cond, a, b):
    """Componentwise (B,)-cond select between two Jacobian points."""
    c = cond[..., None]
    return tuple(jnp.where(c, x, y) for x, y in zip(a, b))


def _pt_add_mixed(X1, Y1, Z1, x2, y2):
    """Jacobian + affine with full exceptional-case handling:
    P identity -> (x2, y2, 1); equal points -> double; inverse -> identity.
    The exceptional double shares stacked multiplies with the add, so the
    whole thing is 18 muls in 6 stacked calls."""
    # interleaved schedule: [add] Z1Z1/U2/S2/H/R chain, [dbl] A/B/C/... chain
    Z1Z1, A, Bv, YZ = _mul_many([(Z1, Z1), (X1, X1), (Y1, Y1), (Y1, Z1)], P_SPEC)
    XB = _add_mod(X1, Bv, P_SPEC)
    E = _add_mod(_add_mod(A, A, P_SPEC), A, P_SPEC)
    U2, Z1c, C, XB2, F = _mul_many(
        [(x2, Z1Z1), (Z1, Z1Z1), (Bv, Bv), (XB, XB), (E, E)], P_SPEC
    )
    D, X3d, C8, Z3d = _dbl2(A, YZ, C, XB2, F)
    S2, EDX3 = _mul_many([(y2, Z1c), (E, _sub_mod(D, X3d, P_SPEC))], P_SPEC)
    Y3d = _sub_mod(EDX3, C8, P_SPEC)  # (X3d, Y3d, Z3d) = 2*(X1,Y1,Z1)
    H = _sub_mod(U2, X1, P_SPEC)
    Rr = _sub_mod(S2, Y1, P_SPEC)
    HH, RR, Z3 = _mul_many([(H, H), (Rr, Rr), (Z1, H)], P_SPEC)
    HHH, V = _mul_many([(H, HH), (X1, HH)], P_SPEC)
    X3 = _sub_mod(_sub_mod(RR, HHH, P_SPEC), _add_mod(V, V, P_SPEC), P_SPEC)
    Y1HHH, RrVX3 = _mul_many(
        [(Y1, HHH), (Rr, _sub_mod(V, X3, P_SPEC))], P_SPEC
    )
    Y3 = _sub_mod(RrVX3, Y1HHH, P_SPEC)

    p_inf = _is_zero(Z1)
    h_zero = _is_zero(H)
    r_zero = _is_zero(Rr)

    one = np.zeros(LIMBS, np.uint32)
    one[0] = 1
    one_l = jnp.broadcast_to(jnp.asarray(one), X1.shape)
    zero_l = jnp.zeros_like(X1)

    out = (X3, Y3, Z3)
    # equal points: the generic formula degenerates -> double instead
    out = _select_pt(h_zero & r_zero & ~p_inf, (X3d, Y3d, Z3d), out)
    # inverse points: identity
    out = _select_pt(h_zero & ~r_zero & ~p_inf, (one_l, one_l, zero_l), out)
    # P was identity: the affine operand
    out = _select_pt(p_inf, (x2, y2, one_l), out)
    return out


def _to_affine(X, Y, Z):
    """(x, y, is_infinity); inversion by Fermat since Z is public."""
    zi = _pow_fixed(Z, _EXP_P_MINUS_2, P_SPEC)
    zi2 = _mul_mod(zi, zi, P_SPEC)
    x = _mul_mod(X, zi2, P_SPEC)
    y = _mul_mod(Y, _mul_mod(zi, zi2, P_SPEC), P_SPEC)
    return x, y, _is_zero(Z)


def _bits_matrix(a: jnp.ndarray) -> jnp.ndarray:
    """(B,16) -> (256, B) scalar bit per ladder step, msb first."""
    return _bits_matrix_w(a, 256)


# ---------------------------------------------------------------------------
# the fused kernel
# ---------------------------------------------------------------------------


def _be_words(v):
    """(B,16) limbs -> (B,8) LE u32 words of the big-endian 32 bytes.
    Consensus-critical: this is the byte layout keccak sees for the
    recovered pubkey (shared by both recovery kernels)."""
    sw = ((v & 0xFF) << 8) | (v >> 8)  # byteswap16 each limb
    hi = sw[:, ::-1]  # most significant limb first
    return hi[:, 0::2] | (hi[:, 1::2] << 16)


@jax.jit
def ecrecover_kernel(e, r, s, parity):
    """Batched ecrecover -> keccak digest of the recovered pubkey.

    Args:
      e: (B,16) uint32 limbs — message-hash scalar (any u256; reduced mod n).
      r, s: (B,16) uint32 limbs — signature fields.
      parity: (B,) uint32 — y-parity of R (recovery id 0/1).

    Returns:
      digest_words: (B, 8) uint32 — keccak256(pubkey_x || pubkey_y) as LE
        u32 words (address = bytes 12..31).
      valid: (B,) bool — r/s in range, x on curve, result not at infinity.
    """
    from phant_tpu.ops.keccak_jax import keccak256_chunked_auto

    B = r.shape[0]
    # varying-axes-safe zero (see _pow_fixed): shard_map scan carries must
    # not start from replicated constants
    zero16 = r ^ r

    # range checks (reference: src/crypto/ecdsa.zig:28-36, sans low-s which
    # is transaction policy, enforced by the signer layer)
    r_ok = ~_is_zero(r) & _lt_const(r, N)
    s_ok = ~_is_zero(s) & _lt_const(s, N)

    # decompress R = lift_x(r, parity): y = (r^3+7)^((p+1)/4)
    x = r  # r < n < p
    x2 = _mul_mod(x, x, P_SPEC)
    x3 = _mul_mod(x2, x, P_SPEC)
    seven = np.zeros(LIMBS, np.uint32)
    seven[0] = 7
    y_sq = _add_mod(x3, jnp.broadcast_to(jnp.asarray(seven), x.shape), P_SPEC)
    y = _pow_fixed(y_sq, _EXP_SQRT, P_SPEC)
    on_curve = _eq(_mul_mod(y, y, P_SPEC), y_sq)
    flip = (y[:, 0] & 1) != (parity & 1)
    y = jnp.where(flip[:, None], _sub_mod(zero16, y, P_SPEC), y)

    # scalars: u1 = -e/r, u2 = s/r (mod n)
    z = _fold(jnp.pad(e, ((0, 0), (0, 16))), N_SPEC)  # e mod n
    r_inv = _pow_fixed(_fold(jnp.pad(r, ((0, 0), (0, 16))), N_SPEC), _EXP_N_MINUS_2, N_SPEC)
    t = _mul_mod(z, r_inv, N_SPEC)
    u1 = jnp.where(_is_zero(t)[:, None], zero16, _sub_mod(zero16, t, N_SPEC))
    u2 = _mul_mod(s, r_inv, N_SPEC)

    # one-off affine G+R (for the Shamir table): full add of two affine pts
    gx = jnp.broadcast_to(jnp.asarray(_G_X), x.shape)
    gy = jnp.broadcast_to(jnp.asarray(_G_Y), x.shape)
    one = np.zeros(LIMBS, np.uint32)
    one[0] = 1
    one_l = jnp.broadcast_to(jnp.asarray(one), x.shape)
    grj = _pt_add_mixed(gx, gy, one_l, x, y)  # G (Z=1) + R
    gr_x, gr_y, gr_inf = _to_affine(*grj)
    # R == G: _pt_add_mixed handled it via its double branch, fine; R == -G
    # yields gr_inf and the ladder skips those adds below.

    # Shamir ladder over msb-first bit pairs
    bits_u1 = _bits_matrix(u1)  # (256, B)
    bits_u2 = _bits_matrix(u2)

    def step(S, bits):
        b1, b2 = bits
        b1 = b1.astype(bool)
        b2 = b2.astype(bool)
        S = _pt_dbl(*S)
        # table select: G / R / G+R
        tx = jnp.where(
            (b1 & b2)[:, None], gr_x, jnp.where(b1[:, None], gx, x)
        )
        ty = jnp.where(
            (b1 & b2)[:, None], gr_y, jnp.where(b1[:, None], gy, y)
        )
        added = _pt_add_mixed(S[0], S[1], S[2], tx, ty)
        skip = (~b1 & ~b2) | (b1 & b2 & gr_inf)
        S = _select_pt(skip, S, added)
        return S, None

    one_v = zero16.at[:, 0].set(1)  # varying-axes-safe identity point
    S0 = (one_v, one_v, zero16)
    Q, _ = jax.lax.scan(step, S0, (bits_u1, bits_u2))

    qx, qy, q_inf = _to_affine(*Q)
    valid = r_ok & s_ok & on_curve & ~q_inf

    words = jnp.zeros((B, 1, 34), jnp.uint32)
    words = words.at[:, 0, 0:8].set(_be_words(qx))
    words = words.at[:, 0, 8:16].set(_be_words(qy))
    words = words.at[:, 0, 16].set(jnp.uint32(0x00000001))  # keccak 0x01 pad
    words = words.at[:, 0, 33].set(jnp.uint32(0x80000000))  # final 0x80
    digest = keccak256_chunked_auto(words, jnp.ones((B,), jnp.int32), max_chunks=1)
    return digest, valid


# ---------------------------------------------------------------------------
# GLV-accelerated kernel
#
# The endomorphism phi(x, y) = (beta*x, y) equals multiplication by lambda
# (lambda^3 = 1 mod n, beta^3 = 1 mod p), so any scalar k splits as
# k = k1 + k2*lambda with |k1|, |k2| <~ 2^128 (lattice basis below, exact
# split verified by tests against bigint math). Q = u1*G + u2*R therefore
# becomes a FOUR-scalar half-width ladder
#     s1*(+-G) + s2*(+-phiG) + t1*(+-R) + t2*(+-phiR)
# over a 16-entry combined table: ~130 doublings instead of 256, one table
# add per step. The mod-n inverse of r and the GLV split are host-side
# bigints (microseconds, and they remove a whole 256-step device ladder).
#
# Exceptional add cases (operands equal / inverse) are astronomically
# impossible for honest signatures but craftable by an adversary who picks
# R = m*G with known m; instead of paying the branch-free exceptional
# machinery on every ladder step, the kernel FLAGS any step whose add
# degenerates and the host replays just those signatures on the exact CPU
# path. Consensus-exact at full speed.
# ---------------------------------------------------------------------------

_GLV_LAMBDA = 0x5363AD4CC05C30E0A5261C028812645A122E22EA20816678DF02967C1B23BD72
_GLV_BETA = 0x7AE96A2B657C07106E64479EAC3434E99CF0497512F58995C1396C28719501EE
_GLV_A1 = 0x3086D221A7D46BCDE86C90E49284EB15
_GLV_B1 = -0xE4437ED6010E88286F547FA90ABFE4C3
_GLV_A2 = 0x114CA50F7A8E2F3F657C1108D9D44CFD8
_GLV_B2 = _GLV_A1
_GLV_BITS = 130  # |ki| <= 2^128 + margin
_GLV_LIMBS = 9  # 144 bits of limb storage

_GLV_CONSTS = None


def _glv_consts():
    """Host-precomputed affine tables: phiG and the four +-G +- phiG combos."""
    global _GLV_CONSTS
    if _GLV_CONSTS is None:
        from phant_tpu.crypto.secp256k1 import _point_add

        phigx = (_GLV_BETA * GX) % P
        cpp = _point_add((GX, GY), (phigx, GY))  # G + phiG
        cpm = _point_add((GX, GY), (phigx, P - GY))  # G - phiG
        # idempotent pure precompute (see _cpu_g2): identical values from
        # any racing writer, evaluated at jit-trace time
        # phantlint: disable=LOCK — benign double-compute of constants
        _GLV_CONSTS = {
            "phig_x": _int_to_limbs_np(phigx),
            "cpp_x": _int_to_limbs_np(cpp[0]),
            "cpp_y": _int_to_limbs_np(cpp[1]),
            "cpm_x": _int_to_limbs_np(cpm[0]),
            "cpm_y": _int_to_limbs_np(cpm[1]),
        }
    return _GLV_CONSTS


def glv_split(k: int) -> Tuple[int, int]:
    """k -> (k1, k2) with k1 + k2*lambda = k (mod n), |ki| <~ 2^128."""
    c1 = (_GLV_B2 * k + N // 2) // N
    c2 = (-_GLV_B1 * k + N // 2) // N
    k1 = k - c1 * _GLV_A1 - c2 * _GLV_A2
    k2 = -c1 * _GLV_B1 - c2 * _GLV_B2
    return k1, k2


def pack_glv_inputs(
    msg_hashes: Sequence[bytes], rs: Sequence[int], ss: Sequence[int]
) -> Tuple[np.ndarray, np.ndarray]:
    """(mags (B,4,9) u32, signs (B,4) u32) for `ecrecover_kernel_glv`: the
    host-bigint half of recovery — r^-1 mod n, u1/u2, and the lambda
    decomposition of each. The single packing recipe shared by the dispatch
    path, the driver dryrun, and the differential tests.

    PRECONDITION (validated here): r, s in (0, N). The device kernel checks
    r's range itself but trusts s entirely — an out-of-range s would pack a
    garbage lambda split and recover to a wrong-but-plausible address, so
    it is rejected at the boundary (_dispatch_glv pre-screens and never
    passes one; direct callers hit this raise)."""
    B = len(msg_hashes)
    for i in range(B):
        if not (0 < rs[i] < N and 0 < ss[i] < N):
            raise ValueError(
                f"signature {i}: r,s must be pre-screened into (0,N) "
                "(ecrecover_kernel_glv trusts the packed split)"
            )
    mags = np.zeros((B, 4, _GLV_LIMBS), np.uint32)
    signs = np.zeros((B, 4), np.uint32)
    for i in range(B):
        z = int.from_bytes(msg_hashes[i], "big") % N
        r_inv = pow(rs[i], -1, N)
        s1, s2 = glv_split((-z * r_inv) % N)
        t1, t2 = glv_split((ss[i] * r_inv) % N)
        mags[i] = _ints_to_limbs_w(
            [abs(s1), abs(s2), abs(t1), abs(t2)], _GLV_LIMBS
        )
        signs[i] = [int(s1 < 0), int(s2 < 0), int(t1 < 0), int(t2 < 0)]
    return mags, signs


def _ints_to_limbs_w(xs: Sequence[int], width: int) -> np.ndarray:
    out = np.zeros((len(xs), width), np.uint32)
    for i, v in enumerate(xs):
        for j in range(width):
            out[i, j] = (v >> (16 * j)) & 0xFFFF
    return out


def _neg_mod_p(v):
    zero = v ^ v
    return jnp.where(_is_zero(v)[:, None], v, _sub_mod(zero, v, P_SPEC))


def _pt_add_plain(X1, Y1, Z1, x2, y2):
    """Jacobian + affine WITHOUT the exceptional-double machinery: 11 muls
    in 4 stacked groups. Returns (X3, Y3, Z3, degenerate) where degenerate
    flags the equal/inverse cases this formula cannot represent (H == 0
    with P finite); callers replay flagged elements on the exact CPU path.
    P at infinity selects the affine operand."""
    (Z1Z1,) = _mul_many([(Z1, Z1)], P_SPEC)
    U2, Z1c = _mul_many([(x2, Z1Z1), (Z1, Z1Z1)], P_SPEC)
    (S2,) = _mul_many([(y2, Z1c)], P_SPEC)
    H = _sub_mod(U2, X1, P_SPEC)
    Rr = _sub_mod(S2, Y1, P_SPEC)
    HH, RR, Z3 = _mul_many([(H, H), (Rr, Rr), (Z1, H)], P_SPEC)
    HHH, V = _mul_many([(H, HH), (X1, HH)], P_SPEC)
    X3 = _sub_mod(_sub_mod(RR, HHH, P_SPEC), _add_mod(V, V, P_SPEC), P_SPEC)
    Y1HHH, RrVX3 = _mul_many([(Y1, HHH), (Rr, _sub_mod(V, X3, P_SPEC))], P_SPEC)
    Y3 = _sub_mod(RrVX3, Y1HHH, P_SPEC)

    p_inf = _is_zero(Z1)
    degenerate = _is_zero(H) & ~p_inf
    one_l = (X1 ^ X1).at[..., 0].set(1)
    out = _select_pt(p_inf, (x2, y2, one_l), (X3, Y3, Z3))
    return out[0], out[1], out[2], degenerate


def _bits_matrix_w(a: jnp.ndarray, nbits: int) -> jnp.ndarray:
    """(B, W) 16-bit limbs -> (nbits, B) bits, msb-first."""
    shifts = jnp.arange(16, dtype=jnp.uint32)
    bits = (a[:, :, None] >> shifts[None, None, :]) & 1  # (B, W, 16)
    flat = bits.reshape(a.shape[0], a.shape[1] * 16)  # lsb-first
    return jnp.flip(flat[:, :nbits], axis=1).T


@jax.jit
def ecrecover_kernel_glv(r, parity, mags, signs):
    """Batched GLV ecrecover -> keccak digest of the recovered pubkey.

    Args:
      r: (B,16) uint32 limbs — signature r (x-coordinate of R).
      parity: (B,) uint32 — y-parity of R.
      mags: (B,4,9) uint32 limbs — |s1|,|s2|,|t1|,|t2| where
        u1 = s1 + s2*lambda, u2 = t1 + t2*lambda (host glv_split).
      signs: (B,4) uint32 — 1 where the corresponding ki is negative.

    Returns (digest_words, valid, degenerate); `degenerate` elements carry
    garbage and must be replayed on the exact CPU path.

    PRECONDITION: mags/signs must come from `pack_glv_inputs` (or an
    equivalent that screened 0 < s < N). The kernel validates r's range and
    curve membership on-device but cannot see s — `valid` does NOT cover an
    out-of-range s, whose split packs to garbage.
    """
    from phant_tpu.ops.keccak_jax import keccak256_chunked_auto

    B = r.shape[0]
    zero16 = r ^ r
    c = _glv_consts()

    r_ok = ~_is_zero(r) & _lt_const(r, N)

    # decompress R
    x = r
    x2 = _mul_mod(x, x, P_SPEC)
    x3 = _mul_mod(x2, x, P_SPEC)
    seven = np.zeros(LIMBS, np.uint32)
    seven[0] = 7
    y_sq = _add_mod(x3, jnp.broadcast_to(jnp.asarray(seven), x.shape), P_SPEC)
    y = _pow_fixed(y_sq, _EXP_SQRT, P_SPEC)
    on_curve = _eq(_mul_mod(y, y, P_SPEC), y_sq)
    flip = (y[:, 0] & 1) != (parity & 1)
    y = jnp.where(flip[:, None], _neg_mod_p(y), y)

    # phiR x-coordinate (one field mul)
    beta = jnp.broadcast_to(jnp.asarray(_int_to_limbs_np(_GLV_BETA)), x.shape)
    xb = _mul_mod(beta, x, P_SPEC)

    sgn = signs.astype(bool)  # (B,4): s1, s2, t1, t2
    neg_y = _neg_mod_p(y)

    gx = jnp.broadcast_to(jnp.asarray(_G_X), x.shape)
    gy = jnp.broadcast_to(jnp.asarray(_G_Y), x.shape)
    phigx = jnp.broadcast_to(jnp.asarray(c["phig_x"]), x.shape)
    neg_gy = _neg_mod_p(gy)

    # G-part entries (affine, per-element sign selects)
    g1x, g1y = gx, jnp.where(sgn[:, 0][:, None], neg_gy, gy)
    g2x, g2y = phigx, jnp.where(sgn[:, 1][:, None], neg_gy, gy)
    # +-G +- phiG combos: (+,+)->Cpp (+,-)->Cpm (-,-)->-Cpp (-,+)->-Cpm
    cppx = jnp.broadcast_to(jnp.asarray(c["cpp_x"]), x.shape)
    cppy = jnp.broadcast_to(jnp.asarray(c["cpp_y"]), x.shape)
    cpmx = jnp.broadcast_to(jnp.asarray(c["cpm_x"]), x.shape)
    cpmy = jnp.broadcast_to(jnp.asarray(c["cpm_y"]), x.shape)
    same = (sgn[:, 0] == sgn[:, 1])[:, None]
    g3x = jnp.where(same, cppx, cpmx)
    g3y = jnp.where(same, cppy, cpmy)
    g3y = jnp.where(sgn[:, 0][:, None], _neg_mod_p(g3y), g3y)

    # R-part entries
    r1x, r1y = x, jnp.where(sgn[:, 2][:, None], neg_y, y)
    r2x, r2y = xb, jnp.where(sgn[:, 3][:, None], neg_y, y)

    one_l = zero16.at[:, 0].set(1)
    degenerate = jnp.zeros((B,), bool)

    # 16-entry table: T[4h+g] = Rc[h] + Gc[g] (Jacobian; Z=0 identity)
    gx_l = [None, g1x, g2x, g3x]
    gy_l = [None, g1y, g2y, g3y]
    TX = [zero16, g1x, g2x, g3x]
    TY = [one_l, g1y, g2y, g3y]
    TZ = [zero16, one_l, one_l, one_l]
    r3x, r3y, r3z, dg = _pt_add_plain(r1x, r1y, one_l, r2x, r2y)
    degenerate = degenerate | dg
    rc = [(r1x, r1y, one_l), (r2x, r2y, one_l), (r3x, r3y, r3z)]
    for h in range(1, 4):
        RX, RY, RZ = rc[h - 1]
        TX.append(RX)
        TY.append(RY)
        TZ.append(RZ)
        for g in range(1, 4):
            X3, Y3, Z3, dg = _pt_add_plain(RX, RY, RZ, gx_l[g], gy_l[g])
            degenerate = degenerate | dg
            TX.append(X3)
            TY.append(Y3)
            TZ.append(Z3)
    Tx = jnp.stack(TX)  # (16, B, 16)
    Ty = jnp.stack(TY)
    Tz = jnp.stack(TZ)

    # normalize the table to affine via one batched inversion (Montgomery
    # trick over the 16 entries; identity Z=0 contributes a neutral 1 and
    # is only ever selected at idx==0, which the ladder skips)
    inf_mask = _is_zero(Tz.reshape(-1, LIMBS)).reshape(16, B, 1)
    z_safe = jnp.where(inf_mask, jnp.broadcast_to(one_l, Tz.shape), Tz)
    prefix = [z_safe[0]]
    for i in range(1, 16):
        (nxt,) = _mul_many([(prefix[-1], z_safe[i])], P_SPEC)
        prefix.append(nxt)
    total_inv = _pow_fixed(prefix[-1], _EXP_P_MINUS_2, P_SPEC)
    zinv = [None] * 16
    acc = total_inv
    for i in range(15, 0, -1):
        zi, acc2 = _mul_many([(acc, prefix[i - 1]), (acc, z_safe[i])], P_SPEC)
        zinv[i] = zi
        acc = acc2
    zinv[0] = acc
    zinv = jnp.stack(zinv)  # (16, B, 16)
    zi2 = _mul_mod(zinv.reshape(-1, LIMBS), zinv.reshape(-1, LIMBS), P_SPEC)
    zi3 = _mul_mod(zi2, zinv.reshape(-1, LIMBS), P_SPEC)
    Tax = _mul_mod(Tx.reshape(-1, LIMBS), zi2, P_SPEC).reshape(16, B, LIMBS)
    Tay = _mul_mod(Ty.reshape(-1, LIMBS), zi3, P_SPEC).reshape(16, B, LIMBS)

    # ladder index per step: s1 + 2*s2 + 4*t1 + 8*t2, msb-first
    b = [_bits_matrix_w(mags[:, i, :], _GLV_BITS) for i in range(4)]
    idx = (b[0] + 2 * b[1] + 4 * b[2] + 8 * b[3]).astype(jnp.int32)  # (130,B)

    def step(carry, idx_t):
        S, deg = carry
        S = _pt_dbl(*S)
        sel = jnp.broadcast_to(idx_t[None, :, None], (1,) + Tax.shape[1:])
        ax = jnp.take_along_axis(Tax, sel, axis=0)[0]
        ay = jnp.take_along_axis(Tay, sel, axis=0)[0]
        X3, Y3, Z3, dg = _pt_add_plain(S[0], S[1], S[2], ax, ay)
        skip = idx_t == 0
        S = _select_pt(skip, S, (X3, Y3, Z3))
        deg = deg | (dg & ~skip)
        return (S, deg), None

    S0 = (one_l, one_l, zero16)
    (Q, deg_ladder), _ = jax.lax.scan(step, (S0, degenerate), idx)
    degenerate = deg_ladder

    qx, qy, q_inf = _to_affine(*Q)
    valid = r_ok & on_curve & ~q_inf

    words = jnp.zeros((B, 1, 34), jnp.uint32)
    words = words.at[:, 0, 0:8].set(_be_words(qx))
    words = words.at[:, 0, 8:16].set(_be_words(qy))
    words = words.at[:, 0, 16].set(jnp.uint32(0x00000001))
    words = words.at[:, 0, 33].set(jnp.uint32(0x80000000))
    digest = keccak256_chunked_auto(words, jnp.ones((B,), jnp.int32), max_chunks=1)
    return digest, valid, degenerate


# ---------------------------------------------------------------------------
# host API
# ---------------------------------------------------------------------------


def ints_to_limbs(xs: Sequence[int]) -> np.ndarray:
    return _ints_to_limbs_w(xs, LIMBS)


def digest_words_to_addresses(words: np.ndarray) -> List[bytes]:
    """(B,8) LE u32 keccak words -> 20-byte addresses (digest bytes 12..31)."""
    arr = np.asarray(words, dtype="<u4")
    return [arr[i].tobytes()[12:32] for i in range(arr.shape[0])]


def ecrecover_batch_async(
    msg_hashes: Sequence[bytes],
    rs: Sequence[int],
    ss: Sequence[int],
    recovery_ids: Sequence[int],
):
    """Dispatch batched ecrecover and return a zero-argument `resolve()`
    callable that materializes the result list. The device computes while
    the host does other work between dispatch and resolve — the building
    block for cross-block pipelining (phant_tpu/blockchain/chain.py
    run_blocks prefetches block N+k's senders while block N executes on
    CPU). recovery_id >= 2 falls back to the CPU backend at dispatch time
    (x = r + n is never produced by Ethereum transactions)."""
    from phant_tpu.crypto.keccak import keccak256
    from phant_tpu.crypto.secp256k1 import SignatureError, recover_pubkey

    B = len(msg_hashes)
    if B == 0:
        return lambda: []
    out: List[Optional[bytes]] = [None] * B
    device_idx = [i for i in range(B) if recovery_ids[i] in (0, 1)]
    for i in range(B):
        if recovery_ids[i] not in (0, 1):
            try:
                pub = recover_pubkey(msg_hashes[i], rs[i], ss[i], recovery_ids[i])
                out[i] = keccak256(pub[1:])[12:]
            except SignatureError:
                out[i] = None
    if not device_idx:
        return lambda: out
    # default = the measured winner: BENCH r4 on a v5e-1 clocked the Shamir
    # interleaved ladder at 5474.5 recoveries/s vs 2666.2/s for the GLV
    # ladder (the endomorphism split halves the ladder length but its extra
    # inversions + wider per-step muxing cost more than it saves at these
    # batch shapes) — GLV stays selectable for A/B runs
    if os.environ.get("PHANT_ECRECOVER_KERNEL", "shamir") == "glv":
        return _dispatch_glv(out, device_idx, msg_hashes, rs, ss, recovery_ids)
    return _dispatch_shamir(out, device_idx, msg_hashes, rs, ss, recovery_ids)


def _bucket_pad(n: int) -> int:
    # power-of-two buckets (>= 32): repeated calls reuse a handful of
    # compiled programs instead of retracing per batch size
    bucket = 32
    while bucket < n:
        bucket *= 2
    return bucket


def _dispatch_shamir(out, device_idx, msg_hashes, rs, ss, recovery_ids):
    """The 256-step Shamir interleaved ladder — the production default
    (BENCH r4: 5474.5/s vs GLV 2666.2/s on a v5e-1)."""
    pad = _bucket_pad(len(device_idx)) - len(device_idx)
    e = ints_to_limbs(
        [int.from_bytes(msg_hashes[i], "big") for i in device_idx] + [1] * pad
    )
    r = ints_to_limbs([rs[i] for i in device_idx] + [1] * pad)
    s = ints_to_limbs([ss[i] for i in device_idx] + [1] * pad)
    par = np.array(
        [recovery_ids[i] & 1 for i in device_idx] + [0] * pad, np.uint32
    )
    digest, valid = ecrecover_kernel(
        jnp.asarray(e), jnp.asarray(r), jnp.asarray(s), jnp.asarray(par)
    )

    def resolve() -> List[Optional[bytes]]:
        # resolve() IS the deliberate sync point of the async dispatch:
        # the caller chose when to materialize (cross-block pipelining)
        addrs = digest_words_to_addresses(np.asarray(digest))  # phantlint: disable=HOSTSYNC — resolve() is the chosen sync point
        valid_np = np.asarray(valid)  # phantlint: disable=HOSTSYNC — resolve() is the chosen sync point
        for k, i in enumerate(device_idx):
            out[i] = addrs[k] if bool(valid_np[k]) else None
        return out

    return resolve


def _dispatch_glv(out, device_idx, msg_hashes, rs, ss, recovery_ids):
    """GLV path: host bigints compute r^-1 and the lambda-decomposition
    (microseconds per signature), the device runs the ~130-step four-scalar
    ladder. Host pre-screens range-invalid signatures and the u1=u2=0
    corner; kernel-flagged degenerate adds (adversarially craftable only)
    replay on the exact CPU path at resolve time."""
    from phant_tpu.crypto.keccak import keccak256
    from phant_tpu.crypto.secp256k1 import SignatureError, recover_pubkey

    ship: List[int] = []
    for i in device_idx:
        if not (0 < rs[i] < N and 0 < ss[i] < N):
            out[i] = None
            continue
        ship.append(i)
    if not ship:
        return lambda: out

    pad = _bucket_pad(len(ship)) - len(ship)
    r_arr = ints_to_limbs([rs[i] for i in ship] + [1] * pad)
    par = np.array([recovery_ids[i] & 1 for i in ship] + [0] * pad, np.uint32)
    mags_s, signs_s = pack_glv_inputs(
        [msg_hashes[i] for i in ship],
        [rs[i] for i in ship],
        [ss[i] for i in ship],
    )
    mags = np.zeros((len(ship) + pad, 4, _GLV_LIMBS), np.uint32)
    mags[: len(ship)] = mags_s
    signs = np.zeros((len(ship) + pad, 4), np.uint32)
    signs[: len(ship)] = signs_s
    digest, valid, degenerate = ecrecover_kernel_glv(
        jnp.asarray(r_arr), jnp.asarray(par), jnp.asarray(mags), jnp.asarray(signs)
    )

    def resolve() -> List[Optional[bytes]]:
        # deliberate sync point (see _dispatch_shamir's resolve)
        addrs = digest_words_to_addresses(np.asarray(digest))  # phantlint: disable=HOSTSYNC — resolve() is the chosen sync point
        valid_np = np.asarray(valid)  # phantlint: disable=HOSTSYNC — resolve() is the chosen sync point
        deg_np = np.asarray(degenerate)  # phantlint: disable=HOSTSYNC — resolve() is the chosen sync point
        for k, i in enumerate(ship):
            if bool(deg_np[k]):  # exact replay for adversarial corner cases
                try:
                    pub = recover_pubkey(
                        msg_hashes[i], rs[i], ss[i], recovery_ids[i]
                    )
                    out[i] = keccak256(pub[1:])[12:]
                except SignatureError:
                    out[i] = None
            else:
                out[i] = addrs[k] if bool(valid_np[k]) else None
        return out

    return resolve


def ecrecover_batch(
    msg_hashes: Sequence[bytes],
    rs: Sequence[int],
    ss: Sequence[int],
    recovery_ids: Sequence[int],
) -> List[Optional[bytes]]:
    """Recover the Ethereum address for each signature on device; None for
    invalid signatures. Synchronous wrapper over ecrecover_batch_async."""
    return ecrecover_batch_async(msg_hashes, rs, ss, recovery_ids)()
