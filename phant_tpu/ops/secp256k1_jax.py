"""Batched secp256k1 ecrecover on TPU via JAX.

The reference recovers one sender at a time through C libsecp256k1
(reference: src/crypto/ecdsa.zig:19-26, called per-tx from
src/signer/signer.zig:40-79). Here the whole recovery — point
decompression, r^-1 mod n, the double-scalar multiplication
Q = u1*G + u2*R (Shamir's trick), Jacobian->affine conversion, and
keccak256(pubkey) -> address — runs on device for a whole batch of
signatures at once (BASELINE.md config #4).

TPU-first design notes:
- u256 values are 16 x 16-bit limbs in uint32 lanes (a 16x16 product fits
  uint32; column sums stay < 2^21, so schoolbook multiply needs no u64).
- Reductions mod p and mod n use the "fold" identity 2^256 ≡ K (mod m)
  for m = 2^256 - K; both moduli are folds + one conditional subtract.
- Modular inverse / square root are fixed-exponent square-and-multiply
  `lax.scan`s over precomputed exponent bits (p-2, (p+1)/4, n-2).
- The 256-step Shamir ladder is a `lax.scan` whose body is one Jacobian
  double + one mixed add + one exceptional double, all branch-free via
  lane selects (identity tracked as Z == 0).
- Everything is fixed-shape; `recovery_id >= 2` (x = r + n, never emitted
  by Ethereum signers) falls back to the CPU backend.

Differential-tested bit-exactly against phant_tpu/crypto/secp256k1.py.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from phant_tpu.crypto.secp256k1 import GX, GY, N, P

LIMBS = 16  # 16-bit limbs per u256
MASK16 = np.uint32(0xFFFF)

K_P = 2**256 - P  # 2^32 + 977
K_N = 2**256 - N


def _int_to_limbs_np(x: int, width: int = LIMBS) -> np.ndarray:
    return np.array([(x >> (16 * j)) & 0xFFFF for j in range(width)], dtype=np.uint32)


def _const_width(x: int) -> int:
    w = 1
    while x >> (16 * w):
        w += 1
    return w


def _bits_msb(x: int, nbits: int = 256) -> np.ndarray:
    return np.array([(x >> (nbits - 1 - i)) & 1 for i in range(nbits)], dtype=np.uint32)


class _ModSpec:
    """Modulus m = 2^256 - K with precomputed fold constant + limb forms."""

    def __init__(self, m: int, folds: int):
        self.m = m
        self.k = 2**256 - m
        self.k_limbs = _int_to_limbs_np(self.k, _const_width(self.k))
        self.m17 = _int_to_limbs_np(m, 17)
        self.folds = folds


P_SPEC = _ModSpec(P, folds=3)  # K_P < 2^33: 3 folds reach < 2m
N_SPEC = _ModSpec(N, folds=4)  # K_N < 2^129: 4 folds reach < 2m

_EXP_P_MINUS_2 = _bits_msb(P - 2)
_EXP_SQRT = _bits_msb((P + 1) // 4)
_EXP_N_MINUS_2 = _bits_msb(N - 2)

_G_X = _int_to_limbs_np(GX)
_G_Y = _int_to_limbs_np(GY)
# 2G, precomputed host-side for the (cryptographically improbable) R == G
# exceptional case of the one-off G+R affine add
_G2 = None  # filled below once CPU helpers are importable


def _cpu_g2() -> Tuple[np.ndarray, np.ndarray]:
    global _G2
    if _G2 is None:
        from phant_tpu.crypto.secp256k1 import _point_add

        g2 = _point_add((GX, GY), (GX, GY))
        _G2 = (_int_to_limbs_np(g2[0]), _int_to_limbs_np(g2[1]))
    return _G2


# ---------------------------------------------------------------------------
# limb arithmetic (all shapes (B, w) uint32 with limbs < 2^16)
# ---------------------------------------------------------------------------


def _carry_unrolled(cols: jnp.ndarray, width: int) -> jnp.ndarray:
    """Propagate carries over `width` columns (statically unrolled so the
    whole thing fuses into one elementwise program; column values must stay
    < 2^31 so `col + carry` cannot overflow uint32)."""
    out = []
    carry = jnp.zeros(cols.shape[:-1], jnp.uint32)
    for i in range(width):
        t = cols[..., i] + carry
        out.append(t & MASK16)
        carry = t >> 16
    return jnp.stack(out, axis=-1), carry


def _pad_cols(x: jnp.ndarray, left: int, width: int) -> jnp.ndarray:
    """Place x's columns at offset `left` in a width-`width` row (static
    shift = concatenation, an elementwise-fusable op — never a scatter)."""
    right = width - left - x.shape[-1]
    pad = [(0, 0)] * (x.ndim - 1) + [(left, right)]
    return jnp.pad(x, pad)


def _mul_wide(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(B,16) x (B,16) -> (B,32) full 512-bit product.

    Schoolbook columns are accumulated with STATIC-shift pads + adds
    instead of `.at[].add` scatters: XLA lowers scatters to slow serialized
    updates on TPU, while pad+add fuses into the elementwise graph. Column
    sums stay < 2^21 (16 lo + 16 hi contributions of < 2^16), so uint32
    accumulation is exact."""
    cols = jnp.zeros(a.shape[:-1] + (33,), jnp.uint32)
    for i in range(LIMBS):
        prod = a[..., i : i + 1] * b  # < 2^32, exact in uint32
        cols = cols + _pad_cols(prod & MASK16, i, 33)
        cols = cols + _pad_cols(prod >> 16, i + 1, 33)
    limbs, carry = _carry_unrolled(cols, 32)
    return limbs  # product < 2^512 so the final carry is 0


def _mul_const(h: jnp.ndarray, k_limbs: np.ndarray) -> jnp.ndarray:
    """(B,w) * constant (k,) -> (B, w+k) exact product (pad+add columns,
    same rationale as _mul_wide)."""
    w = h.shape[-1]
    k = len(k_limbs)
    kk = jnp.asarray(k_limbs)
    width = w + k + 1
    cols = jnp.zeros(h.shape[:-1] + (width,), jnp.uint32)
    for i in range(w):
        prod = h[..., i : i + 1] * kk
        cols = cols + _pad_cols(prod & MASK16, i, width)
        cols = cols + _pad_cols(prod >> 16, i + 1, width)
    limbs, _ = _carry_unrolled(cols, w + k)
    return limbs


def _add_wide(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(B,wa) + (B,wb) -> (B, max+1)."""
    w = max(a.shape[-1], b.shape[-1])
    pa = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, w - a.shape[-1])])
    pb = jnp.pad(b, [(0, 0)] * (b.ndim - 1) + [(0, w - b.shape[-1])])
    limbs, carry = _carry_unrolled(pa + pb, w)
    return jnp.concatenate([limbs, carry[..., None]], axis=-1)


def _sub_borrow(a: jnp.ndarray, b: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """a - b limbwise; returns (difference, borrowed) with equal widths."""
    w = a.shape[-1]
    ai = a.astype(jnp.int32)
    bi = b.astype(jnp.int32)
    out = []
    borrow = jnp.zeros(a.shape[:-1], jnp.int32)
    for i in range(w):
        t = ai[..., i] - bi[..., i] - borrow
        out.append((t & 0xFFFF).astype(jnp.uint32))
        borrow = (t < 0).astype(jnp.int32)
    return jnp.stack(out, axis=-1), borrow > 0


def _cond_sub(a: jnp.ndarray, m_limbs: np.ndarray) -> jnp.ndarray:
    """a mod-subtract the constant m once if a >= m (same width)."""
    m = jnp.asarray(m_limbs)
    m = jnp.broadcast_to(m, a.shape)
    d, borrowed = _sub_borrow(a, m)
    return jnp.where(borrowed[..., None], a, d)


def _fold(x: jnp.ndarray, spec: _ModSpec) -> jnp.ndarray:
    """Reduce a wide value to (B,16) using 2^256 ≡ K (mod m)."""
    for _ in range(spec.folds):
        if x.shape[-1] <= LIMBS:
            break
        lo = x[..., :LIMBS]
        hi = x[..., LIMBS:]
        x = _add_wide(lo, _mul_const(hi, spec.k_limbs))
    # width is now <= 17 and value < 2m
    w = x.shape[-1]
    if w < 17:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, 17 - w)])
    x = _cond_sub(x[..., :17], spec.m17)
    return x[..., :LIMBS]


def _mul_mod(a, b, spec: _ModSpec):
    return _fold(_mul_wide(a, b), spec)


def _add_mod(a, b, spec: _ModSpec):
    return _fold(_add_wide(a, b), spec)


def _sub_mod(a, b, spec: _ModSpec):
    d, borrowed = _sub_borrow(a, b)
    m = jnp.broadcast_to(jnp.asarray(_int_to_limbs_np(spec.m)), d.shape)
    limbs, _ = _carry_unrolled(d + m, LIMBS)
    return jnp.where(borrowed[..., None], limbs, d)


def _is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == 0, axis=-1)


def _eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == b, axis=-1)


def _lt_const(a: jnp.ndarray, m: int) -> jnp.ndarray:
    """a < m (for range checks against n)."""
    _, borrowed = _sub_borrow(a, jnp.broadcast_to(jnp.asarray(_int_to_limbs_np(m)), a.shape))
    return borrowed


def _pow_fixed(base: jnp.ndarray, exp_bits: np.ndarray, spec: _ModSpec) -> jnp.ndarray:
    """base^e for a fixed public exponent, square-and-multiply lax.scan."""
    base = jnp.asarray(base)
    # derive the initial accumulator from the input so it inherits the
    # input's varying manual axes under shard_map (a fresh constant would be
    # replicated and break the scan carry typing)
    acc0 = (base ^ base).at[..., 0].set(1)

    def body(acc, bit):
        acc = _mul_mod(acc, acc, spec)
        with_mul = _mul_mod(acc, base, spec)
        return jnp.where(bit.astype(bool), with_mul, acc), None

    acc, _ = jax.lax.scan(body, acc0, jnp.asarray(exp_bits))
    return acc


# ---------------------------------------------------------------------------
# point arithmetic (Jacobian; identity is Z == 0)
#
# Independent field multiplications are stacked along the batch axis into a
# single wider multiply (`_mul_many`) — same FLOPs, ~3x fewer HLO ops, which
# cuts XLA compile time of the 256-step ladder dramatically.
# ---------------------------------------------------------------------------


def _mul_many(pairs, spec: _ModSpec):
    """[(a1,b1),(a2,b2),...] -> [a1*b1, a2*b2, ...] via one stacked multiply."""
    if len(pairs) == 1:
        return [_mul_mod(pairs[0][0], pairs[0][1], spec)]
    a = jnp.concatenate([p[0] for p in pairs], axis=0)
    b = jnp.concatenate([p[1] for p in pairs], axis=0)
    out = _mul_mod(a, b, spec)
    B = pairs[0][0].shape[0]
    return [out[i * B : (i + 1) * B] for i in range(len(pairs))]


def _dbl2(A, YZ, C, XB2, F):
    """Assemble the doubling result from its precomputed products."""
    D = _sub_mod(_sub_mod(XB2, A, P_SPEC), C, P_SPEC)
    D = _add_mod(D, D, P_SPEC)  # 2((X+B)^2 - A - C)
    X3 = _sub_mod(_sub_mod(F, D, P_SPEC), D, P_SPEC)
    C8 = _add_mod(C, C, P_SPEC)
    C8 = _add_mod(C8, C8, P_SPEC)
    C8 = _add_mod(C8, C8, P_SPEC)
    Z3 = _add_mod(YZ, YZ, P_SPEC)
    return D, X3, C8, Z3


def _pt_dbl(X, Y, Z):
    """Jacobian doubling for y^2 = x^3 + 7 (a = 0); 7 muls in 3 stacked
    calls. Maps identity (Z=0) to identity and (x,0) to identity (Z'=2YZ)."""
    A, Bv, YZ = _mul_many([(X, X), (Y, Y), (Y, Z)], P_SPEC)
    XB = _add_mod(X, Bv, P_SPEC)
    E = _add_mod(_add_mod(A, A, P_SPEC), A, P_SPEC)  # 3A
    C, XB2, F = _mul_many([(Bv, Bv), (XB, XB), (E, E)], P_SPEC)
    D, X3, C8, Z3 = _dbl2(A, YZ, C, XB2, F)
    (EDX3,) = _mul_many([(E, _sub_mod(D, X3, P_SPEC))], P_SPEC)
    Y3 = _sub_mod(EDX3, C8, P_SPEC)
    return X3, Y3, Z3


def _select_pt(cond, a, b):
    """Componentwise (B,)-cond select between two Jacobian points."""
    c = cond[..., None]
    return tuple(jnp.where(c, x, y) for x, y in zip(a, b))


def _pt_add_mixed(X1, Y1, Z1, x2, y2):
    """Jacobian + affine with full exceptional-case handling:
    P identity -> (x2, y2, 1); equal points -> double; inverse -> identity.
    The exceptional double shares stacked multiplies with the add, so the
    whole thing is 18 muls in 6 stacked calls."""
    # interleaved schedule: [add] Z1Z1/U2/S2/H/R chain, [dbl] A/B/C/... chain
    Z1Z1, A, Bv, YZ = _mul_many([(Z1, Z1), (X1, X1), (Y1, Y1), (Y1, Z1)], P_SPEC)
    XB = _add_mod(X1, Bv, P_SPEC)
    E = _add_mod(_add_mod(A, A, P_SPEC), A, P_SPEC)
    U2, Z1c, C, XB2, F = _mul_many(
        [(x2, Z1Z1), (Z1, Z1Z1), (Bv, Bv), (XB, XB), (E, E)], P_SPEC
    )
    D, X3d, C8, Z3d = _dbl2(A, YZ, C, XB2, F)
    S2, EDX3 = _mul_many([(y2, Z1c), (E, _sub_mod(D, X3d, P_SPEC))], P_SPEC)
    Y3d = _sub_mod(EDX3, C8, P_SPEC)  # (X3d, Y3d, Z3d) = 2*(X1,Y1,Z1)
    H = _sub_mod(U2, X1, P_SPEC)
    Rr = _sub_mod(S2, Y1, P_SPEC)
    HH, RR, Z3 = _mul_many([(H, H), (Rr, Rr), (Z1, H)], P_SPEC)
    HHH, V = _mul_many([(H, HH), (X1, HH)], P_SPEC)
    X3 = _sub_mod(_sub_mod(RR, HHH, P_SPEC), _add_mod(V, V, P_SPEC), P_SPEC)
    Y1HHH, RrVX3 = _mul_many(
        [(Y1, HHH), (Rr, _sub_mod(V, X3, P_SPEC))], P_SPEC
    )
    Y3 = _sub_mod(RrVX3, Y1HHH, P_SPEC)

    p_inf = _is_zero(Z1)
    h_zero = _is_zero(H)
    r_zero = _is_zero(Rr)

    one = np.zeros(LIMBS, np.uint32)
    one[0] = 1
    one_l = jnp.broadcast_to(jnp.asarray(one), X1.shape)
    zero_l = jnp.zeros_like(X1)

    out = (X3, Y3, Z3)
    # equal points: the generic formula degenerates -> double instead
    out = _select_pt(h_zero & r_zero & ~p_inf, (X3d, Y3d, Z3d), out)
    # inverse points: identity
    out = _select_pt(h_zero & ~r_zero & ~p_inf, (one_l, one_l, zero_l), out)
    # P was identity: the affine operand
    out = _select_pt(p_inf, (x2, y2, one_l), out)
    return out


def _to_affine(X, Y, Z):
    """(x, y, is_infinity); inversion by Fermat since Z is public."""
    zi = _pow_fixed(Z, _EXP_P_MINUS_2, P_SPEC)
    zi2 = _mul_mod(zi, zi, P_SPEC)
    x = _mul_mod(X, zi2, P_SPEC)
    y = _mul_mod(Y, _mul_mod(zi, zi2, P_SPEC), P_SPEC)
    return x, y, _is_zero(Z)


def _bits_matrix(a: jnp.ndarray) -> jnp.ndarray:
    """(B,16) -> (256, B) scalar bit per ladder step, msb first."""
    shifts = jnp.arange(16, dtype=jnp.uint32)
    bits = (a[:, :, None] >> shifts[None, None, :]) & 1  # (B, 16, 16)
    flat = bits.reshape(a.shape[0], 256)  # lsb-first
    return jnp.flip(flat, axis=1).T  # (256, B) msb-first


# ---------------------------------------------------------------------------
# the fused kernel
# ---------------------------------------------------------------------------


@jax.jit
def ecrecover_kernel(e, r, s, parity):
    """Batched ecrecover -> keccak digest of the recovered pubkey.

    Args:
      e: (B,16) uint32 limbs — message-hash scalar (any u256; reduced mod n).
      r, s: (B,16) uint32 limbs — signature fields.
      parity: (B,) uint32 — y-parity of R (recovery id 0/1).

    Returns:
      digest_words: (B, 8) uint32 — keccak256(pubkey_x || pubkey_y) as LE
        u32 words (address = bytes 12..31).
      valid: (B,) bool — r/s in range, x on curve, result not at infinity.
    """
    from phant_tpu.ops.keccak_jax import keccak256_chunked

    B = r.shape[0]
    # varying-axes-safe zero (see _pow_fixed): shard_map scan carries must
    # not start from replicated constants
    zero16 = r ^ r

    # range checks (reference: src/crypto/ecdsa.zig:28-36, sans low-s which
    # is transaction policy, enforced by the signer layer)
    r_ok = ~_is_zero(r) & _lt_const(r, N)
    s_ok = ~_is_zero(s) & _lt_const(s, N)

    # decompress R = lift_x(r, parity): y = (r^3+7)^((p+1)/4)
    x = r  # r < n < p
    x2 = _mul_mod(x, x, P_SPEC)
    x3 = _mul_mod(x2, x, P_SPEC)
    seven = np.zeros(LIMBS, np.uint32)
    seven[0] = 7
    y_sq = _add_mod(x3, jnp.broadcast_to(jnp.asarray(seven), x.shape), P_SPEC)
    y = _pow_fixed(y_sq, _EXP_SQRT, P_SPEC)
    on_curve = _eq(_mul_mod(y, y, P_SPEC), y_sq)
    flip = (y[:, 0] & 1) != (parity & 1)
    y = jnp.where(flip[:, None], _sub_mod(zero16, y, P_SPEC), y)

    # scalars: u1 = -e/r, u2 = s/r (mod n)
    z = _fold(jnp.pad(e, ((0, 0), (0, 16))), N_SPEC)  # e mod n
    r_inv = _pow_fixed(_fold(jnp.pad(r, ((0, 0), (0, 16))), N_SPEC), _EXP_N_MINUS_2, N_SPEC)
    t = _mul_mod(z, r_inv, N_SPEC)
    u1 = jnp.where(_is_zero(t)[:, None], zero16, _sub_mod(zero16, t, N_SPEC))
    u2 = _mul_mod(s, r_inv, N_SPEC)

    # one-off affine G+R (for the Shamir table): full add of two affine pts
    gx = jnp.broadcast_to(jnp.asarray(_G_X), x.shape)
    gy = jnp.broadcast_to(jnp.asarray(_G_Y), x.shape)
    one = np.zeros(LIMBS, np.uint32)
    one[0] = 1
    one_l = jnp.broadcast_to(jnp.asarray(one), x.shape)
    grj = _pt_add_mixed(gx, gy, one_l, x, y)  # G (Z=1) + R
    gr_x, gr_y, gr_inf = _to_affine(*grj)
    # R == G: _pt_add_mixed handled it via its double branch, fine; R == -G
    # yields gr_inf and the ladder skips those adds below.

    # Shamir ladder over msb-first bit pairs
    bits_u1 = _bits_matrix(u1)  # (256, B)
    bits_u2 = _bits_matrix(u2)

    def step(S, bits):
        b1, b2 = bits
        b1 = b1.astype(bool)
        b2 = b2.astype(bool)
        S = _pt_dbl(*S)
        # table select: G / R / G+R
        tx = jnp.where(
            (b1 & b2)[:, None], gr_x, jnp.where(b1[:, None], gx, x)
        )
        ty = jnp.where(
            (b1 & b2)[:, None], gr_y, jnp.where(b1[:, None], gy, y)
        )
        added = _pt_add_mixed(S[0], S[1], S[2], tx, ty)
        skip = (~b1 & ~b2) | (b1 & b2 & gr_inf)
        S = _select_pt(skip, S, added)
        return S, None

    one_v = zero16.at[:, 0].set(1)  # varying-axes-safe identity point
    S0 = (one_v, one_v, zero16)
    Q, _ = jax.lax.scan(step, S0, (bits_u1, bits_u2))

    qx, qy, q_inf = _to_affine(*Q)
    valid = r_ok & s_ok & on_curve & ~q_inf

    # pubkey (64 bytes big-endian) -> keccak words (LE u32) on device
    def be_words(v):  # (B,16) limbs -> (B,8) LE u32 words of the BE bytes
        sw = ((v & 0xFF) << 8) | (v >> 8)  # byteswap16 each limb
        hi = sw[:, ::-1]  # most significant limb first
        return hi[:, 0::2] | (hi[:, 1::2] << 16)

    words = jnp.zeros((B, 1, 34), jnp.uint32)
    words = words.at[:, 0, 0:8].set(be_words(qx))
    words = words.at[:, 0, 8:16].set(be_words(qy))
    words = words.at[:, 0, 16].set(jnp.uint32(0x00000001))  # keccak 0x01 pad
    words = words.at[:, 0, 33].set(jnp.uint32(0x80000000))  # final 0x80
    digest = keccak256_chunked(words, jnp.ones((B,), jnp.int32), max_chunks=1)
    return digest, valid


# ---------------------------------------------------------------------------
# host API
# ---------------------------------------------------------------------------


def ints_to_limbs(xs: Sequence[int]) -> np.ndarray:
    out = np.zeros((len(xs), LIMBS), np.uint32)
    for i, v in enumerate(xs):
        for j in range(LIMBS):
            out[i, j] = (v >> (16 * j)) & 0xFFFF
    return out


def digest_words_to_addresses(words: np.ndarray) -> List[bytes]:
    """(B,8) LE u32 keccak words -> 20-byte addresses (digest bytes 12..31)."""
    arr = np.asarray(words, dtype="<u4")
    return [arr[i].tobytes()[12:32] for i in range(arr.shape[0])]


def ecrecover_batch_async(
    msg_hashes: Sequence[bytes],
    rs: Sequence[int],
    ss: Sequence[int],
    recovery_ids: Sequence[int],
):
    """Dispatch batched ecrecover and return a zero-argument `resolve()`
    callable that materializes the result list. The device computes while
    the host does other work between dispatch and resolve — the building
    block for cross-block pipelining (phant_tpu/blockchain/chain.py
    run_blocks prefetches block N+k's senders while block N executes on
    CPU). recovery_id >= 2 falls back to the CPU backend at dispatch time
    (x = r + n is never produced by Ethereum transactions)."""
    from phant_tpu.crypto.keccak import keccak256
    from phant_tpu.crypto.secp256k1 import SignatureError, recover_pubkey

    B = len(msg_hashes)
    if B == 0:
        return lambda: []
    out: List[Optional[bytes]] = [None] * B
    device_idx = [i for i in range(B) if recovery_ids[i] in (0, 1)]
    for i in range(B):
        if recovery_ids[i] not in (0, 1):
            try:
                pub = recover_pubkey(msg_hashes[i], rs[i], ss[i], recovery_ids[i])
                out[i] = keccak256(pub[1:])[12:]
            except SignatureError:
                out[i] = None
    if not device_idx:
        return lambda: out
    # bucket the batch to a power of two (>= 32) so repeated calls reuse a
    # handful of compiled programs instead of retracing per batch size
    bucket = 32
    while bucket < len(device_idx):
        bucket *= 2
    pad = bucket - len(device_idx)
    e = ints_to_limbs(
        [int.from_bytes(msg_hashes[i], "big") for i in device_idx] + [1] * pad
    )
    r = ints_to_limbs([rs[i] for i in device_idx] + [1] * pad)
    s = ints_to_limbs([ss[i] for i in device_idx] + [1] * pad)
    par = np.array(
        [recovery_ids[i] & 1 for i in device_idx] + [0] * pad, np.uint32
    )
    digest, valid = ecrecover_kernel(
        jnp.asarray(e), jnp.asarray(r), jnp.asarray(s), jnp.asarray(par)
    )

    def resolve() -> List[Optional[bytes]]:
        addrs = digest_words_to_addresses(np.asarray(digest))
        valid_np = np.asarray(valid)
        for k, i in enumerate(device_idx):
            out[i] = addrs[k] if bool(valid_np[k]) else None
        return out

    return resolve


def ecrecover_batch(
    msg_hashes: Sequence[bytes],
    rs: Sequence[int],
    ss: Sequence[int],
    recovery_ids: Sequence[int],
) -> List[Optional[bytes]]:
    """Recover the Ethereum address for each signature on device; None for
    invalid signatures. Synchronous wrapper over ecrecover_batch_async."""
    return ecrecover_batch_async(msg_hashes, rs, ss, recovery_ids)()
