"""Device-side MPT root recomputation (level-by-level keccak).

Recomputes a Merkle Patricia Trie root with every keccak256 on device: the
host walks the built trie once and emits a *hash plan* — per-level RLP node
templates with 32-byte holes where child digests belong — and the device
then alternates (scatter child digests into the blob) -> (batched keccak of
the level) until the root digest falls out. Host->device traffic is the
template blob once plus tiny per-level index arrays; all hashing (the hot
~90% of CPU root computation) happens on the chip.

This is BASELINE.md metric #2 (state-root recompute): the reference computes
roots serially on CPU (reference: src/mpt/mpt.zig:38-119, keccak per node)
and skips state-root verification entirely (reference:
src/blockchain/blockchain.zig:83-85).

Scope: tries whose nodes all RLP-encode to >= 32 bytes (true for the secure
state trie — account leaves are ~70B — and for receipt/tx tries of real
blocks). Tries with embedded (<32B) nodes fall back to the CPU walk.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from phant_tpu import rlp
from phant_tpu.crypto.keccak import RATE
from phant_tpu.mpt.mpt import (
    BranchNode,
    EMPTY_TRIE_ROOT,
    ExtensionNode,
    LeafNode,
    Trie,
    encode_hex_prefix,
)
from phant_tpu.ops.witness_jax import _pow2ceil as _pow2, witness_digests

# state-trie branch nodes are <= 17*33 + 2 bytes; 5 rate chunks cover 676B
MPT_MAX_CHUNKS = 5

_HOLE = object()  # placeholder for a child digest in a node template


def _list_header(payload_len: int) -> bytes:
    if payload_len < 56:
        return bytes([0xC0 + payload_len])
    ll = payload_len.to_bytes((payload_len.bit_length() + 7) // 8, "big")
    return bytes([0xF7 + len(ll)]) + ll


def str_header(payload_len: int) -> bytes:
    """RLP string header for a payload of `payload_len` >= 2 bytes (the
    single-byte encodings below 0x80 never apply to the >=33-byte account
    leaf values this is used for)."""
    if payload_len < 56:
        return bytes([0x80 + payload_len])
    ll = payload_len.to_bytes((payload_len.bit_length() + 7) // 8, "big")
    return bytes([0xB7 + len(ll)]) + ll


class _ValueHole:
    """A leaf VALUE carrying an embedded 32-byte hole: RLP-encodes as one
    string item `prefix + <32 zero bytes> + suffix`, with the hole's byte
    offset reported like a child-ref hole. This is how the fused post-root
    plan wires an account leaf to its storage trie's root digest — the
    storage root is a hole INSIDE the leaf's account-RLP value
    (stateless.WitnessStateDB.post_root_plan)."""

    __slots__ = ("prefix", "suffix")

    def __init__(self, prefix: bytes, suffix: bytes):
        self.prefix = prefix
        self.suffix = suffix


def _encode_template(items) -> Tuple[bytes, List[int]]:
    """RLP-encode a node whose child refs are 32-byte holes; returns the
    encoding (holes zeroed) and each hole's byte offset (in encounter
    order — standalone `_HOLE` items and `_ValueHole` inner holes alike)."""
    payload = bytearray()
    holes: List[int] = []
    for it in items:
        if it is _HOLE:
            payload.append(0xA0)  # RLP string header for 32 bytes
            holes.append(len(payload))
            payload += b"\x00" * 32
        elif isinstance(it, _ValueHole):
            total = len(it.prefix) + 32 + len(it.suffix)
            payload += str_header(total)
            payload += it.prefix
            holes.append(len(payload))
            payload += b"\x00" * 32
            payload += it.suffix
        else:
            payload += rlp.encode(it)
    header = _list_header(len(payload))
    return bytes(header) + bytes(payload), [h + len(header) for h in holes]


@dataclass
class HashPlan:
    """Per-level device layout for one (or one fused set of) trie(s).

    The plan is value-complete but hash-free: templates carry zeroed 32-byte
    holes where child digests go, so executing the plan re-derives EVERY
    node digest from raw bytes — caching a plan caches packing work, never
    hashes. `device_args` holds the plan's arrays already resident on the
    device (populated on first execution), so repeated roots of an unchanged
    trie transfer nothing but the 32-byte result.

    `out_rows` lists the digest rows (in the PADDED per-level row space)
    the caller wants back — the fused post-root plans read back each
    storage root plus the account root; None means just the root."""

    blob: np.ndarray  # (L,) uint8 — all templates + gather/scatter slack
    # per level: offsets (n,), lens (n,), hole_pos (h,), hole_child (h,)
    levels: List[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]
    n_nodes: int  # total real nodes
    root_pos: int  # row of the root digest in the global digest buffer
    device_args: Optional[tuple] = None  # (blob_d, levels_d) jax arrays
    out_rows: Optional[np.ndarray] = None  # (R,) int32 padded-space rows


class PlanBuilder:
    """Shared post-order template walker behind `build_hash_plan` (full
    tries) and the PartialTrie post-root planner (stateless.py).

    Two extensions over the original full-trie walk make witness-shaped
    (partial) tries plannable:

      * a node exposing a `.digest` attribute (an unwitnessed HashNode
        subtree) contributes its digest to the parent template as a
        CONSTANT — no entry, no hashing: the untouched subtrees of a
        witness enter the level blob as literal bytes;
      * a LeafNode registered in `value_holes` encodes its value as a
        `_ValueHole` — 32 zero bytes wired to another planned entry's
        digest row — which is how one fused plan covers account AND
        storage tries (the storage root is a hole in the account leaf).

    `try_subtree` visits one trie with rollback: a subtree containing an
    embedded (<32 B) or oversized node unwinds cleanly so the caller can
    fall back to the host walk for THAT trie only.

    Scheme hooks (phant_tpu/commitment/): `_path_enc` encodes a leaf/
    extension path into its template (hex-prefix here; bit-prefix for the
    binary scheme's BinaryPlanBuilder) and `_min_template` carries the
    embedded-node rule (32 for hexary MPT — a <32 B encoding would have
    been embedded in its parent, so a digest-per-node plan would be wrong;
    0 for schemes that ALWAYS reference children by digest). Everything
    else — level layout, hole wiring, `finish`, `merge_plans`, the device
    executors — is scheme-independent: a HashPlan is just templates with
    32-byte holes at byte offsets."""

    #: leaf/extension path encoding (hexary default: hex-prefix)
    _path_enc = staticmethod(encode_hex_prefix)
    #: smallest plannable template (the hexary embedded-node rule)
    _min_template = 32

    def __init__(self):
        # (level, template, [(hole_off, child_gi)])
        self.entries: List[Tuple[int, bytes, List[Tuple[int, int]]]] = []
        self._index: Dict[int, int] = {}
        self._order: List[int] = []  # node ids, parallel to entries
        self.too_small = False
        # id(LeafNode) -> (value_prefix, value_suffix, child_gi,
        # child_level): the fused account+storage wiring
        self.value_holes: Dict[int, Tuple[bytes, bytes, int, int]] = {}

    def visit(self, node) -> Tuple[Optional[int], int, Optional[bytes]]:
        """(entry_gi, level, const_digest). `const_digest` is set (and gi
        is None, level 0) for digest-only nodes."""
        dg = getattr(node, "digest", None)
        if dg is not None:
            return None, 0, dg
        nid = id(node)
        if nid in self._index:
            gi = self._index[nid]
            return gi, self.entries[gi][0], None
        if isinstance(node, LeafNode):
            vh = self.value_holes.get(nid)
            if vh is not None:
                prefix, suffix, child_gi, child_level = vh
                template, holes = _encode_template(
                    [self._path_enc(node.path, True), _ValueHole(prefix, suffix)]
                )
                level = child_level + 1
                hole_refs: List[Tuple[int, int]] = [(holes[0], child_gi)]
            else:
                template, _holes = _encode_template(
                    [self._path_enc(node.path, True), node.value]
                )
                level = 0
                hole_refs = []
        elif isinstance(node, ExtensionNode):
            ci, clvl, cdg = self.visit(node.child)
            if cdg is not None:
                template, _holes = _encode_template(
                    [self._path_enc(node.path, False), cdg]
                )
                level = 0
                hole_refs = []
            else:
                template, holes = _encode_template(
                    [self._path_enc(node.path, False), _HOLE]
                )
                level = clvl + 1
                hole_refs = [(holes[0], ci)]
        else:  # BranchNode
            items: List = []
            child_order: List[int] = []
            level = -1
            for child in node.children:
                if child is None:
                    items.append(b"")
                    continue
                ci, clvl, cdg = self.visit(child)
                if cdg is not None:
                    items.append(cdg)  # constant 32-byte digest ref
                else:
                    items.append(_HOLE)
                    child_order.append(ci)
                    level = max(level, clvl)
            items.append(node.value if node.value is not None else b"")
            template, holes = _encode_template(items)
            level += 1  # -1 (all-constant children) -> level 0
            hole_refs = list(zip(holes, child_order))
        if len(template) < self._min_template:
            self.too_small = True
        if len(template) > MPT_MAX_CHUNKS * RATE - 1:
            self.too_small = True  # oversized node: CPU path
        gi = len(self.entries)
        self.entries.append((level, template, hole_refs))
        self._index[nid] = gi
        self._order.append(nid)
        return gi, level, None

    def try_subtree(self, node) -> Optional[Tuple[int, int]]:
        """Visit one trie root; (gi, level), or None with the builder
        rolled back when the subtree is unplannable (embedded/oversized
        node, or a digest-only root)."""
        mark = len(self.entries)
        saved = self.too_small
        self.too_small = False
        gi, level, const = self.visit(node)
        if self.too_small or const is not None:
            del self.entries[mark:]
            for nid in self._order[mark:]:
                self._index.pop(nid, None)
            del self._order[mark:]
            self.too_small = saved
            return None
        self.too_small = saved
        return gi, level

    def finish(
        self, root_gi: int, out_gis: Sequence[int] = ()
    ) -> Optional[HashPlan]:
        """Lay the visited entries into the per-level device layout.
        `out_gis` selects extra entries whose digest rows the caller wants
        read back (`HashPlan.out_rows`; the root row is appended last)."""
        if self.too_small or not self.entries:
            return None
        entries = self.entries
        n = len(entries)
        offsets = np.zeros(n, np.int64)
        pos = 0
        for gi, (_lvl, template, _holes) in enumerate(entries):
            offsets[gi] = pos
            pos += len(template)
        # pow2-pad the blob so repeated roots of similar tries hit a small
        # set of compiled shapes (the slack doubles as scatter scratch)
        blob = np.zeros(_pow2(pos + MPT_MAX_CHUNKS * RATE), np.uint8)
        for gi, (_lvl, template, _holes) in enumerate(entries):
            blob[offsets[gi] : offsets[gi] + len(template)] = np.frombuffer(
                template, np.uint8
            )

        max_level = max(lvl for lvl, _t, _h in entries)
        levels = []
        # digest rows are laid out level by level, each level padded to a
        # power of two — remap must use the PADDED cumulative position,
        # since that is where the fused executor writes each level's rows
        remap = np.zeros(n, np.int64)
        next_global = 0
        scratch = len(blob) - 32  # scatter target for hole padding rows
        for lvl in range(max_level + 1):
            idxs = [gi for gi in range(n) if entries[gi][0] == lvl]
            for k, gi in enumerate(idxs):
                remap[gi] = next_global + k
            npad = _pow2(len(idxs))
            off = np.zeros(npad, np.int32)
            ln = np.zeros(npad, np.int32)
            for k, gi in enumerate(idxs):
                off[k] = offsets[gi]
                ln[k] = len(entries[gi][1])
            hp: List[int] = []
            hc: List[int] = []
            for gi in idxs:
                for hole_off, child_gi in entries[gi][2]:
                    hp.append(int(offsets[gi]) + hole_off)
                    hc.append(int(remap[child_gi]))
            hpad = _pow2(len(hp)) if hp else 1
            hole_pos = np.full(hpad, scratch, np.int32)
            hole_child = np.zeros(hpad, np.int32)
            hole_pos[: len(hp)] = hp
            hole_child[: len(hc)] = hc
            levels.append((off, ln, hole_pos, hole_child))
            next_global += npad
        # the root is the unique max-level node (level(parent) >
        # level(child) for every edge — including the value-hole edges —
        # and all planned nodes descend from the root)
        top_real = [gi for gi in range(n) if entries[gi][0] == max_level]
        assert top_real == [root_gi]
        out_rows = None
        if out_gis:
            out_rows = np.asarray(
                [int(remap[g]) for g in out_gis], np.int32
            )
        return HashPlan(
            blob=blob,
            levels=levels,
            n_nodes=n,
            root_pos=int(remap[root_gi]),
            out_rows=out_rows,
        )


def plan_payload_bytes(plan: HashPlan) -> int:
    """Total template bytes of one plan — the shippable payload weighed by
    the offload gate (ops/root_engine.py) and the scheduler's root-job
    byte accounting; the pow2 blob padding is slack, not payload. ONE
    definition so the two can never drift."""
    return int(sum(int(ln.sum()) for _o, ln, _h, _c in plan.levels))


def build_hash_plan(trie: Trie) -> Optional[HashPlan]:
    """Walk the trie into a HashPlan, or None when any node encodes < 32B
    (embedded-node rule: those tries take the CPU path)."""
    if trie.root is None:
        return None
    builder = PlanBuilder()
    res = builder.try_subtree(trie.root)
    if res is None:
        return None
    return builder.finish(res[0])


def merge_plans(
    plans: Sequence[HashPlan], blob_out: Optional[np.ndarray] = None
) -> Tuple[HashPlan, List[np.ndarray]]:
    """K independent HashPlans fused into ONE level-aligned device plan —
    the cross-request coalescing behind the serving post-root path
    (ops/root_engine.py): level l of the merged plan is the concatenation
    of every input plan's level l, so one dispatch hashes all K requests'
    dirty subtrees with max(depth) sequential keccak rounds instead of K
    round trips. Row/hole indices are remapped into the merged padded row
    space; per-plan blob regions keep their own scatter slack, so pad
    holes stay harmless.

    Returns (merged plan, per-input-plan merged out_rows — same order as
    each plan's own out_rows, defaulting to [root]). `blob_out` hands in
    a pre-zeroed pooled buffer at least the merged pow2 size (the serving
    staging lease); omitted, a fresh buffer is allocated."""
    shifts: List[int] = []
    pos = 0
    for p in plans:
        shifts.append(pos)
        pos += len(p.blob)
    need = _pow2(pos + MPT_MAX_CHUNKS * RATE)
    if blob_out is not None:
        if len(blob_out) < need:
            raise ValueError("merge blob lease too small")
        blob = blob_out
    else:
        blob = np.zeros(need, np.uint8)
    for p, sp in zip(plans, shifts):
        blob[sp : sp + len(p.blob)] = p.blob

    n_levels = max(len(p.levels) for p in plans)
    # local padded-row -> merged padded-row maps (pad rows map to 0; only
    # pad holes reference them and those are dropped below)
    local_maps = [
        np.zeros(sum(len(off) for off, _l, _p, _c in p.levels), np.int64)
        for p in plans
    ]
    local_starts: List[List[int]] = []
    for p in plans:
        starts: List[int] = []
        s = 0
        for off, _l, _p2, _c in p.levels:
            starts.append(s)
            s += len(off)
        local_starts.append(starts)

    merged_levels = []
    merged_start = 0
    scratch = len(blob) - 32
    for lvl in range(n_levels):
        offs: List[np.ndarray] = []
        lns: List[np.ndarray] = []
        hps: List[np.ndarray] = []
        hcs: List[np.ndarray] = []
        n_real_tot = 0
        for pi, p in enumerate(plans):
            if lvl >= len(p.levels):
                continue
            off, ln, hp, hc = p.levels[lvl]
            n_real = int(np.count_nonzero(ln))
            if n_real:
                local_maps[pi][
                    local_starts[pi][lvl] : local_starts[pi][lvl] + n_real
                ] = merged_start + n_real_tot + np.arange(n_real)
                offs.append(off[:n_real] + shifts[pi])
                lns.append(ln[:n_real])
            n_real_tot += n_real
            # real holes only: pad holes point at the plan's own scratch
            real_h = hp != (len(p.blob) - 32)
            if real_h.any():
                hps.append(hp[real_h] + shifts[pi])
                # children live at strictly lower levels, already mapped
                hcs.append(local_maps[pi][hc[real_h]])
        npad = _pow2(max(n_real_tot, 1))
        moff = np.zeros(npad, np.int32)
        mln = np.zeros(npad, np.int32)
        if offs:
            moff[:n_real_tot] = np.concatenate(offs)
            mln[:n_real_tot] = np.concatenate(lns)
        nh = sum(len(h) for h in hps)
        hpad = _pow2(nh) if nh else 1
        mhp = np.full(hpad, scratch, np.int32)
        mhc = np.zeros(hpad, np.int32)
        if nh:
            mhp[:nh] = np.concatenate(hps)
            mhc[:nh] = np.concatenate(hcs)
        merged_levels.append((moff, mln, mhp, mhc))
        merged_start += npad

    outs: List[np.ndarray] = []
    for pi, p in enumerate(plans):
        rows = (
            p.out_rows
            if p.out_rows is not None
            else np.asarray([p.root_pos], np.int32)
        )
        outs.append(local_maps[pi][rows].astype(np.int32))
    merged = HashPlan(
        blob=blob,
        levels=merged_levels,
        n_nodes=sum(p.n_nodes for p in plans),
        root_pos=int(local_maps[-1][plans[-1].root_pos]),
        out_rows=np.concatenate(outs).astype(np.int32),
    )
    return merged, outs


# ---------------------------------------------------------------------------
# device executor
# ---------------------------------------------------------------------------


def plan_digests_host(plan: HashPlan) -> np.ndarray:
    """CPU mirror of the fused device executor: recompute EVERY node digest
    from the plan's templates (scatter child digests into the holes, batch
    keccak each level through the native library). This is the honest CPU
    baseline for the device state-root path — identical inputs, identical
    recompute-all-hashes semantics, best available host implementation
    (no RLP re-encoding, one keccak FFI batch per level). Returns the full
    (total_pad, 32) u8 digest buffer in the padded row space."""
    from phant_tpu.crypto.keccak import keccak256
    from phant_tpu.utils.native import load_native

    native = load_native()
    blob = plan.blob.copy()
    total_pad = sum(len(off) for off, _l, _p, _c in plan.levels)
    digests = np.zeros((total_pad, 32), np.uint8)
    out_start = 0
    pos32 = np.arange(32)
    for off, ln, hole_pos, hole_child in plan.levels:
        child = digests[hole_child]  # (H, 32)
        blob[hole_pos[:, None] + pos32[None, :]] = child
        payloads = [
            blob[off[k] : off[k] + ln[k]].tobytes() for k in range(len(off))
        ]
        if native is not None:
            hashed = native.keccak256_batch_fast(payloads)
        else:
            hashed = [keccak256(p) for p in payloads]
        digests[out_start : out_start + len(off)] = [
            np.frombuffer(h, np.uint8) for h in hashed
        ]
        out_start += len(off)
    return digests


def execute_plan_host(plan: HashPlan) -> bytes:
    """Host plan execution returning the root digest (see
    plan_digests_host)."""
    return plan_digests_host(plan)[plan.root_pos].tobytes()


def execute_plan_outputs_host(plan: HashPlan) -> List[bytes]:
    """Host plan execution returning the `out_rows` digests (root-only
    when the plan has none) — the CPU twin of `_hash_plan_outputs`."""
    digests = plan_digests_host(plan)
    rows = (
        plan.out_rows
        if plan.out_rows is not None
        else np.asarray([plan.root_pos], np.int64)
    )
    return [digests[int(r)].tobytes() for r in rows]


def _plan_digests_body(blob, levels, *, max_chunks: int):
    """Execute a whole HashPlan in ONE device program: for each level
    (statically unrolled; shapes are the jit cache key) scatter the child
    digests into the template holes, hash the level with the batched keccak
    kernel, and append to the digest buffer. One dispatch replaces the
    per-level round trips of the old executor — on a high-latency link that
    is the difference between ~1x and ~{levels}x RTT per root. Returns the
    full (total_pad, 8) u32 digest buffer."""
    total_pad = sum(off.shape[0] for off, _l, _p, _c in levels)
    digests = jnp.zeros((total_pad, 8), jnp.uint32)
    shifts = jnp.arange(4, dtype=jnp.uint32) * 8
    pos32 = jnp.arange(32, dtype=jnp.int32)
    out_start = 0
    for off, ln, hole_pos, hole_child in levels:
        d = digests[hole_child]  # (H, 8)
        dbytes = ((d[:, :, None] >> shifts[None, None, :]) & 0xFF).astype(jnp.uint8)
        flat = hole_pos[:, None] + pos32[None, :]
        blob = blob.at[flat.reshape(-1)].set(dbytes.reshape(-1))
        level_digests = witness_digests(blob, off, ln, max_chunks=max_chunks)
        digests = jax.lax.dynamic_update_slice(
            digests, level_digests, (out_start, 0)
        )
        out_start += off.shape[0]
    return digests


def _hash_plan_body(blob, levels, *, max_chunks: int):
    """(8,) u32 root digest words (the root is the unique max-level node,
    laid out last by PlanBuilder.finish). Unjitted body so
    `_hash_plans_batched` can vmap it over a batch of blobs; the scalar
    entry point `_hash_plan_fused` wraps it in jit."""
    return _plan_digests_body(blob, levels, max_chunks=max_chunks)[-1]


@functools.partial(jax.jit, static_argnames=("max_chunks",))
def _hash_plan_outputs(blob, levels, out_rows, *, max_chunks: int):
    """Full-plan execution returning only the requested digest rows —
    the serving post-root executor (ops/root_engine.py): one dispatch
    hashes a MERGED multi-request plan and reads back each request's
    storage roots + account root ((R, 8) u32), nothing else."""
    return _plan_digests_body(blob, levels, max_chunks=max_chunks)[out_rows]


_hash_plan_fused = functools.partial(jax.jit, static_argnames=("max_chunks",))(
    _hash_plan_body
)


@functools.partial(jax.jit, static_argnames=("max_chunks",))
def _hash_plans_batched(blobs, levels, *, max_chunks: int):
    """K state roots in ONE dispatch: vmap the fused plan executor over a
    (K, L) batch of template blobs sharing one level layout. This is the
    production shape for block replay — K consecutive block states of the
    same account trie differ only in leaf *values*, so the structural plan
    (offsets/holes) is shared and only the blobs vary. Amortizes the
    host->device round trip over K roots (the per-root RTT is what the
    offload gate rejects at K=1 on a tunneled link)."""
    return jax.vmap(
        lambda b: _hash_plan_body(b, levels, max_chunks=max_chunks)
    )(blobs)


def plans_share_structure(a: HashPlan, b: HashPlan) -> bool:
    """True when two plans have identical level layouts (offsets, lengths,
    hole positions, hole children) and blob sizes — the precondition for
    vmapping them through one `_hash_plans_batched` dispatch. Consecutive
    block states of the same account trie share structure whenever only
    fixed-width leaf values changed; an account birth/death or a
    variable-width RLP growth breaks the run. The replay segment lowerer
    (phant_tpu/replay/lowering.py) uses this to group a segment's
    per-block plans into maximal batchable runs instead of failing the
    whole segment on the first mismatch."""
    if len(a.blob) != len(b.blob) or len(a.levels) != len(b.levels):
        return False
    for (o1, l1, h1, c1), (o2, l2, h2, c2) in zip(a.levels, b.levels):
        if (
            o1.shape != o2.shape
            or not np.array_equal(o1, o2)
            or not np.array_equal(l1, l2)
            or not np.array_equal(h1, h2)
            or not np.array_equal(c1, c2)
        ):
            return False
    return True


def trie_roots_device_batched(plans: List[HashPlan]) -> List[bytes]:
    """Roots for K same-structure plans (identical level layouts, differing
    blobs) in one fused device dispatch. Raises ValueError if the plans'
    layouts differ (callers batch consecutive block states, which share
    structure by construction when leaf values are fixed-width)."""
    if not plans:
        return []
    ref = plans[0]
    for p in plans[1:]:
        if not plans_share_structure(p, ref):
            raise ValueError("batched plans must share structure")
    blobs = jnp.asarray(np.stack([p.blob for p in plans]))
    # per-LEVEL metadata uploads, bounded by trie depth (~8 tiny arrays) —
    # not a data-axis loop; the node axis itself ships in the one blob above
    levels_d = tuple(tuple(jnp.asarray(a) for a in lvl) for lvl in ref.levels)  # phantlint: disable=JNPHOSTLOOP — bounded per-level metadata upload
    roots = _hash_plans_batched(blobs, levels_d, max_chunks=MPT_MAX_CHUNKS)
    arr = np.asarray(roots, dtype="<u4")
    return [arr[k].tobytes() for k in range(arr.shape[0])]


def trie_root_device(trie: Trie, plan: Optional[HashPlan] = None) -> bytes:
    """Trie root with all keccak hashing on device in a single fused
    dispatch; CPU fallback for tries with embedded nodes.

    Plans are cached on the trie per mutation epoch (phant_tpu/mpt/mpt.py
    bumps `_epoch` on put/delete): an unchanged trie re-executes the full
    hash pipeline from device-resident templates — every digest is
    recomputed on device each call, only the host packing is reused."""
    if trie.root is None:
        return EMPTY_TRIE_ROOT
    if plan is None:
        epoch = getattr(trie, "_epoch", None)
        cached = getattr(trie, "_device_plan", None)
        if cached is not None and epoch is not None and cached[0] == epoch:
            plan = cached[1]
        else:
            plan = build_hash_plan(trie)
            if plan is not None and epoch is not None:
                trie._device_plan = (epoch, plan)
    if plan is None:
        return trie.root_hash()

    if plan.device_args is None:
        # memoized ONCE per plan; bounded by trie depth like the batched twin
        levels_d = tuple(
            tuple(jnp.asarray(a) for a in lvl) for lvl in plan.levels  # phantlint: disable=JNPHOSTLOOP — bounded per-level metadata upload
        )
        plan.device_args = (jnp.asarray(plan.blob), levels_d)
    blob_d, levels_d = plan.device_args
    assert plan.root_pos == sum(len(off) for off, _l, _p, _c in plan.levels) - 1
    root_words = _hash_plan_fused(blob_d, levels_d, max_chunks=MPT_MAX_CHUNKS)
    # the 32-byte root is the product — this readback is the function's
    # contract, not an accidental sync
    return np.asarray(root_words, dtype="<u4").tobytes()  # phantlint: disable=HOSTSYNC — root readback is the product
