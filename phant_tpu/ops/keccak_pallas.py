"""Batched keccak256 as a hand-tiled Pallas TPU kernel.

This kernel keeps the whole sponge state in VMEM/vregs for the entire
absorb loop: one grid step owns a tile of SUB*128 hash instances, reads
their padded rate chunks once from its VMEM block, and writes only the
8-word digests back.  Slope-timed on a v5e-1 it does 44.4M hashes/s at
MPT node shapes (~13.5 GB/s of keccak input) — 1.25x the jnp/XLA program
in ops/keccak_jax.py and ~34x the host 8-way AVX-512 batch.  (r4's
conclusion that the device keccak loses to the host was a measurement
artifact: per-call forced readbacks over the dev tunnel time the ~30-70ms
round trip, not the ~0.4ms kernel — see bench.py _slope_time_chunked.)

Layout: instances are laid across (sublane, lane) = (SUB, 128) tiles —
each Keccak lane half is a full (SUB, 128) u32 vector, so every bitwise
op in the round function is a dense VPU op with zero cross-lane traffic
(Keccak's permutation never mixes instances; rotations are static shifts
within each u32 pair).

Differential-tested bit-exactly against the CPU/native backends
(tests/test_keccak_pallas.py).  Reference scope equivalence:
src/crypto/hasher.zig:4-17 — the batching axis and the device path are
this framework's addition per the north star (SURVEY §7.8a).
"""

from __future__ import annotations

import functools
import threading as _threading
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from phant_tpu.ops.keccak_jax import (
    _RC_HI,
    _RC_LO,
    _ROT,
    RATE_WORDS,
    _rotl64,
)

# instances per grid step = SUB * 128.  8 sublanes is the native u32 tile
# and the measured winner: the slope-timed sweep on a v5e-1 (16384-instance
# 5-chunk batch, ground-truth-verified chained timing) measured SUB=8/16/32
# at 44.4 / 40.5 / 33.0 M hashes/s.
import os as _os

_SUB = int(_os.environ.get("PHANT_KECCAK_PALLAS_SUB", "8"))

# interpreter mode: lets the CPU-mesh test suite differentially verify the
# kernel body without Mosaic/TPU (slow — tests only)
_INTERPRET = _os.environ.get("PHANT_PALLAS_INTERPRET", "0") == "1"


def _round_body(lo: List, hi: List, rc_lo, rc_hi) -> None:
    """One Keccak-f[1600] round, in place; RC is a traced scalar.

    Same structure as keccak_jax._keccak_round.  Kept as the fori_loop
    body: unrolling all 24 rounds per chunk blows the kernel past ~25k
    vector ops, where Mosaic's scheduling falls off a ~400x cliff
    (measured on a v5e-1: C=2 unrolled 240M perms/s, C=3 unrolled 0.7M).
    """
    # theta
    clo = [lo[x] ^ lo[x + 5] ^ lo[x + 10] ^ lo[x + 15] ^ lo[x + 20] for x in range(5)]
    chi_ = [hi[x] ^ hi[x + 5] ^ hi[x + 10] ^ hi[x + 15] ^ hi[x + 20] for x in range(5)]
    for x in range(5):
        r1lo, r1hi = _rotl64(clo[(x + 1) % 5], chi_[(x + 1) % 5], 1)
        dlo = clo[(x - 1) % 5] ^ r1lo
        dhi = chi_[(x - 1) % 5] ^ r1hi
        for y in range(5):
            lo[x + 5 * y] = lo[x + 5 * y] ^ dlo
            hi[x + 5 * y] = hi[x + 5 * y] ^ dhi
    # rho + pi
    blo: List = [None] * 25
    bhi: List = [None] * 25
    for x in range(5):
        for y in range(5):
            src = x + 5 * y
            dst = y + 5 * ((2 * x + 3 * y) % 5)
            blo[dst], bhi[dst] = _rotl64(lo[src], hi[src], _ROT[src])
    # chi
    for y in range(5):
        row_lo = [blo[x + 5 * y] for x in range(5)]
        row_hi = [bhi[x + 5 * y] for x in range(5)]
        for x in range(5):
            lo[x + 5 * y] = row_lo[x] ^ (~row_lo[(x + 1) % 5] & row_lo[(x + 2) % 5])
            hi[x + 5 * y] = row_hi[x] ^ (~row_hi[(x + 1) % 5] & row_hi[(x + 2) % 5])
    # iota
    lo[0] = lo[0] ^ rc_lo
    hi[0] = hi[0] ^ rc_hi


def _f1600(lo: List, hi: List, rc_ref) -> tuple:
    """24 rounds as a fori_loop carrying the 50-vector state in vregs."""

    def body(rnd, carry):
        lo_t, hi_t = carry
        lo_l, hi_l = list(lo_t), list(hi_t)
        _round_body(lo_l, hi_l, rc_ref[rnd, 0], rc_ref[rnd, 1])
        return (tuple(lo_l), tuple(hi_l))

    lo_t, hi_t = jax.lax.fori_loop(0, 24, body, (tuple(lo), tuple(hi)))
    return list(lo_t), list(hi_t)


def _make_kernel(max_chunks: int):
    def kernel(words_ref, nch_ref, rc_ref, out_ref):
        # words_ref: (1, C, 34, SUB, 128) u32 — rate chunks, word-major
        # nch_ref:   (1, SUB, 128) i32     — live chunk count per instance
        # rc_ref:    (24, 2) u32 in SMEM   — round constants (lo, hi)
        # out_ref:   (1, 8, SUB, 128) u32  — digest words
        nch = nch_ref[0]
        zeros = jnp.zeros((_SUB, 128), jnp.uint32)
        lo = [zeros] * 25
        hi = [zeros] * 25
        for c in range(max_chunks):
            nlo = list(lo)
            nhi = list(hi)
            for i in range(RATE_WORDS):
                nlo[i] = nlo[i] ^ words_ref[0, c, 2 * i]
                nhi[i] = nhi[i] ^ words_ref[0, c, 2 * i + 1]
            nlo, nhi = _f1600(nlo, nhi, rc_ref)
            if c == 0:
                lo, hi = nlo, nhi  # every payload has >= 1 chunk
            else:
                live = nch > c
                lo = [jnp.where(live, n, o) for n, o in zip(nlo, lo)]
                hi = [jnp.where(live, n, o) for n, o in zip(nhi, hi)]
        for i in range(4):
            out_ref[0, 2 * i] = lo[i]
            out_ref[0, 2 * i + 1] = hi[i]

    return kernel


@functools.partial(jax.jit, static_argnames=("max_chunks",))
def keccak256_chunked_pallas(
    words: jax.Array, nchunks: jax.Array, *, max_chunks: int
) -> jax.Array:
    """Drop-in for keccak_jax.keccak256_chunked on the Pallas path.

    Args:
      words: (B, max_chunks, 34) uint32 — keccak-padded rate chunks (LE u32).
      nchunks: (B,) int32 — live chunks per instance (>= 1).
      max_chunks: static bucket bound.

    Returns:
      (B, 8) uint32 digests, bit-identical to the jnp and CPU backends.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B = words.shape[0]
    C = max_chunks
    tile = _SUB * 128
    Bp = -(-B // tile) * tile  # pad batch to a whole number of tiles
    if Bp != B:
        words = jnp.pad(words, ((0, Bp - B), (0, 0), (0, 0)))
        # padded instances absorb chunk 0 of zeros (harmless, discarded)
        nchunks = jnp.pad(nchunks, (0, Bp - B), constant_values=1)
    nt = Bp // tile
    # instance b = (t, s, l): words -> (NT, C, 34, SUB, 128), one transpose
    # on device (cheap, HBM-bandwidth) so each kernel read is a dense tile
    w = words.reshape(nt, _SUB, 128, C, 34).transpose(0, 3, 4, 1, 2)
    n = nchunks.astype(jnp.int32).reshape(nt, _SUB, 128)
    rc = jnp.asarray(np.stack([_RC_LO, _RC_HI], axis=1))  # (24, 2) u32

    out = pl.pallas_call(
        _make_kernel(C),
        grid=(nt,),
        in_specs=[
            pl.BlockSpec(
                (1, C, 34, _SUB, 128),
                lambda t: (t, 0, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((1, _SUB, 128), lambda t: (t, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((24, 2), lambda t: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, 8, _SUB, 128), lambda t: (t, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((nt, 8, _SUB, 128), jnp.uint32),
        interpret=_INTERPRET,
    )(w, n, rc)
    return out.transpose(0, 2, 3, 1).reshape(Bp, 8)[:B]


_PALLAS_OK: bool | None = None
_probe_lock = _threading.Lock()


def pallas_available() -> bool:
    """Whether the Pallas TPU path compiles+runs on this host's backend.

    Mosaic requires a real TPU (or the interpreter); on the CPU-mesh test
    backend callers fall back to the jnp kernel.  Probed once per process
    with a tiny shape, lock-serialized (phantlint LOCK) so concurrent
    first dispatches don't both pay the Mosaic trial compile.
    """
    global _PALLAS_OK
    if _PALLAS_OK is None:
        with _probe_lock:
            if _PALLAS_OK is not None:
                return _PALLAS_OK
            try:
                import jax

                if jax.default_backend() == "cpu" and not _INTERPRET:
                    _PALLAS_OK = False
                else:
                    w = jnp.zeros((1, 1, 34), jnp.uint32)
                    n = jnp.ones((1,), jnp.int32)
                    # the probe VERIFIES the kernel runs — the block is the
                    # point, and holding _probe_lock across it is too: a
                    # second thread must WAIT for the one probe, not run its
                    # own (the memo exists to pay this exactly once)
                    keccak256_chunked_pallas(w, n, max_chunks=1).block_until_ready()  # phantlint: disable=HOSTSYNC,LOCKBLOCK — one-shot Mosaic probe
                    _PALLAS_OK = True
            except Exception:
                _PALLAS_OK = False
    return _PALLAS_OK


