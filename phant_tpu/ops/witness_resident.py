"""Device-resident witness intern table: upload novel bytes once, ever.

The memoized engine (ops/witness_engine.py) already hashes each unique
trie node once — but on the TPU route it still pays the link per batch:
novel bytes go up, their digests come back down, and the linkage join
runs on HOST tables, so the chip holds no state and contributes nothing
in the steady state (the ROADMAP "device-resident intern table" gap:
91.9M hashes/s on the kernel, ~zero end-to-end, because the tunnel —
not the compute — is on the per-batch critical path).

This module keeps the intern table ON the device, persistent across
batches:

  * **Resident rows** — `digests` (cap, 8) u32, the child-reference
    words `refs` (cap, 17, 8) and their liveness (cap, 17), one row per
    unique interned node, scattered in place by the update program the
    moment a novel batch is dispatched. Rows are assigned by the HOST
    (`slot_of_bytes`, the authoritative commit — exact byte equality,
    no fingerprint trust on the verdict path) and grow in power-of-two
    generations; a generation FLUSH drops everything and is synchronized
    with the owning engine's host-table flushes, so host and device
    tables never disagree about what exists.
  * **Row index** — a hash-bucketed open-addressing table over 64-bit
    digest fingerprints (ops/keccak_jax.index_insert / index_lookup),
    resident next to the rows. The production verdict never needs it
    (host rows are exact); it is the DEVICE-side scan: the chained
    slope protocol resolves rows on device from fingerprints alone
    (8 bytes/node up, nothing else), and tests cross-check it against
    the host dict.
  * **Per-batch traffic** — truly-novel bytes (the host scan prunes
    anything already resident, including cross-batch pipelined
    duplicates the engine cores re-report) + 4 bytes/node of row ids +
    32 bytes/block of roots up; 1 byte/block of verdicts + 32 bytes per
    CORE-novel digest down (the engine's host tables commit from the
    device digests, so the host hashes nothing on this route). Steady
    state: row ids and roots only — the PAPERS.md 2408.14217 reuse
    analysis is exactly why that is a small fraction of witness bytes.

Verdict semantics are identical to the host engine's linkage join and
the fused kernel (a block verifies iff some node's digest equals its
root AND every node is that root or hash-referenced by a same-block
node); a row the device cannot resolve FAILS its block — residency can
only reject, never silently accept. Differential-tested against all
three engine cores in tests/test_witness_resident.py.

Thread-safety: one lock guards the host bookkeeping and the array
handles; dispatches enqueue under it (async — no device sync inside the
lock) so concurrent engines/schedulers see a consistent row space, and
data dependencies between the update and verdict programs serialize the
device work regardless of thread interleaving. The lock never takes the
engine lock (the engine calls in, never the reverse).
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from phant_tpu.utils.trace import metrics
from phant_tpu.ops.witness_jax import WITNESS_MAX_CHUNKS, _pow2ceil

__all__ = [
    "ResidentBatch",
    "ResidentTable",
    "resident_default_cap",
    "slope_time_resident",
]


def resident_default_cap() -> int:
    """PHANT_RESIDENT_CAP: hard row bound of a resident table (~613 B of
    HBM per row: digest + 17 ref words + liveness + fingerprint + 2
    index buckets). The default fits comfortably in a v5e's 16 GB."""
    return int(os.environ.get("PHANT_RESIDENT_CAP", 1 << 20))


# ---------------------------------------------------------------------------
# device programs (compose keccak + ref extraction + index primitives)
# ---------------------------------------------------------------------------


def _update_impl(digests, refs, ref_live, index, fps, blob, offsets, lens, slots, *, max_chunks):
    """Scatter one novel batch into the resident arrays: hash the nodes,
    extract their child references, write rows at the host-assigned
    slots, insert digest fingerprints into the index. Pad rows carry
    slot -1 and drop out of bounds."""
    import jax.numpy as jnp

    from phant_tpu.ops.keccak_jax import index_insert
    from phant_tpu.ops.witness_jax import witness_node_features

    cap = digests.shape[0]
    d, r, rl = witness_node_features(blob, offsets, lens, max_chunks=max_chunks)
    ok = slots >= 0
    tgt = jnp.where(ok, slots, cap)  # out of bounds -> dropped by the mode
    digests = digests.at[tgt].set(d, mode="drop")
    refs = refs.at[tgt].set(r, mode="drop")
    ref_live = ref_live.at[tgt].set(rl, mode="drop")
    fps = fps.at[tgt].set(d[:, :2], mode="drop")
    index, dropped = index_insert(index, d[:, :2], slots, ok)
    return digests, refs, ref_live, index, fps, dropped


def _verdict_impl(digests, refs, ref_live, rows, node_live, block_id, roots):
    """(n_blocks,) bool linked-multiproof verdict from resident rows.

    `node_live` marks real nodes (False = batch padding); a live node
    whose row is unresolved (< 0) fails its block — the device-lookup
    mode can MISS, and a miss must reject, exactly like a witness
    missing that node. Semantics otherwise identical to
    witness_jax.linked_verdict / the host engine join."""
    import jax.numpy as jnp

    from phant_tpu.ops.witness_jax import _referenced

    cap = digests.shape[0]
    n_blocks = roots.shape[0]
    present = node_live & (rows >= 0)
    rc = jnp.clip(rows, 0, cap - 1)
    d = digests[rc]  # (B, 8); garbage for non-present rows, masked below
    r17 = refs[rc]  # (B, 17, 8)
    rl = (ref_live[rc] & present[:, None]).reshape(-1)
    rb = jnp.broadcast_to(block_id[:, None], (rows.shape[0], 17)).reshape(-1)
    is_root = jnp.all(d == roots[block_id], axis=1) & present
    referenced = _referenced(d, block_id, r17.reshape(-1, 8), rb, rl)
    ok_node = (~node_live) | (present & (is_root | referenced))
    root_hit = (
        jnp.zeros((n_blocks,), jnp.int32)
        .at[block_id]
        .max(is_root.astype(jnp.int32))
    )
    all_ok = (
        jnp.ones((n_blocks,), jnp.int32)
        .at[jnp.where(node_live, block_id, 0)]
        .min(jnp.where(node_live, ok_node, True).astype(jnp.int32))
    )
    return (root_hit > 0) & (all_ok > 0)


def _reindex_impl(fps, n_rows):
    """Fresh index over the first `n_rows` fingerprints (pow2 growth
    rehashes: bucket positions depend on the table size)."""
    import jax.numpy as jnp

    from phant_tpu.ops.keccak_jax import INDEX_EMPTY, index_insert

    cap = fps.shape[0]
    slots = jnp.arange(cap, dtype=jnp.int32)
    index = jnp.full((4 * cap,), INDEX_EMPTY, jnp.int32)
    return index_insert(index, fps, slots, slots < n_rows)


def _gather_impl(digests, slots):
    """(N, 8) digest rows at `slots` (clipped; callers slice real rows)."""
    import jax.numpy as jnp

    return digests[jnp.clip(slots, 0, digests.shape[0] - 1)]


def _lookup_impl(index, fps, q):
    from phant_tpu.ops.keccak_jax import index_lookup

    return index_lookup(index, fps, q)


_JIT_PROGRAMS: dict = {}
_JIT_LOCK = threading.Lock()


def _jit_programs(donate: bool) -> dict:
    """The jitted resident programs, memoized per donation mode (which
    is a per-backend property, so in practice one entry per process)."""
    with _JIT_LOCK:
        fns = _JIT_PROGRAMS.get(donate)
        if fns is None:
            import jax

            fns = _JIT_PROGRAMS[donate] = {
                "update": jax.jit(
                    _update_impl,
                    static_argnames=("max_chunks",),
                    donate_argnums=(0, 1, 2, 3, 4) if donate else (),
                ),
                "verdict": jax.jit(_verdict_impl),
                "reindex": jax.jit(_reindex_impl),
                "gather": jax.jit(_gather_impl),
                "lookup": jax.jit(_lookup_impl),
            }
        return fns


class ResidentBatch:
    """One dispatched resident batch: the verdict bits and (when the
    engine core had novel nodes) their digest rows, both still on
    device. `resolve()` pays the readback — verdicts are 1 byte/block,
    digests 32 bytes per core-novel node; in the steady state that is
    the ONLY downlink traffic of witness verification."""

    __slots__ = (
        "verdict_out",
        "digest_out",
        "dropped_outs",
        "n_blocks",
        "n_core_novel",
        "uploaded_nodes",
        "uploaded_bytes",
        "generation",
        "_table",
        "resolved",
    )

    def __init__(self):
        for name in self.__slots__:
            setattr(self, name, None)
        self.dropped_outs = []
        self.resolved = False

    def resolve(self) -> Tuple[np.ndarray, List[bytes]]:
        """(verdicts, core_novel_digests) — the honest sync of the
        resident route."""
        from phant_tpu.ops.keccak_jax import digests_to_bytes

        with metrics.phase("witness_resident.resolve"):
            # the timed verdict readback IS the honest sync (1 B/block)
            verdicts = np.asarray(self.verdict_out)[: self.n_blocks]  # phantlint: disable=HOSTSYNC — timed resident verdict readback
            digests: List[bytes] = []
            if self.digest_out is not None:
                digests = digests_to_bytes(np.asarray(self.digest_out))[  # phantlint: disable=HOSTSYNC — timed core-commit digest readback
                    : self.n_core_novel
                ]
        dropped = 0
        for out in self.dropped_outs:
            dropped += int(np.asarray(out))  # phantlint: disable=HOSTSYNC — rides the resolve sync above
        if dropped and self._table is not None:
            self._table.note_index_dropped(dropped)
        self.resolved = True
        self.dropped_outs = []
        self.verdict_out = None  # release the device outputs
        self.digest_out = None
        return verdicts.astype(bool), digests


class ResidentTable:
    """The device-resident intern table of ONE engine (or one mesh lane:
    device-pinned engines each own an independent table on their chip).
    """

    def __init__(
        self,
        max_cap: Optional[int] = None,
        start_cap: Optional[int] = None,
        device=None,
    ):
        self._max_cap = _pow2ceil(max_cap or resident_default_cap())
        if start_cap is None:
            # PHANT_RESIDENT_START_CAP: pre-size the row space when the
            # working set is known (the bench does — growth recompiles
            # the update program per pow2 step, which must not land in a
            # timed pass)
            start_cap = int(os.environ.get("PHANT_RESIDENT_START_CAP", 1 << 10))
        self._start_cap = min(_pow2ceil(max(start_cap, 64)), self._max_cap)
        self._device = device  # jax device handle or None (default placement)
        self._lock = threading.Lock()
        #: the authoritative commit: exact node bytes -> resident row.
        #: Byte objects are shared references with the engine core's own
        #: dict, so the marginal host memory is dict overhead, not copies.
        self._slot_of_bytes: Dict[bytes, int] = {}
        self._n_rows = 0
        self._cap = 0
        self._arrays = None  # (digests, refs, ref_live, index, fps)
        self._deferred_dropped: list = []  # reindex drop counts, unread
        self.generation = 0
        self.stats = {
            "uploaded_nodes": 0,
            "uploaded_bytes": 0,
            "pruned_nodes": 0,
            "batches": 0,
            "grows": 0,
            "flushes": 0,
            "index_dropped": 0,
        }
        # jitted programs: PROCESS-level singletons (not per-table — a
        # mesh pool builds one table per lane, and per-table jit wrappers
        # would recompile the same HLO once per lane). Buffer DONATION is
        # enabled on real accelerators so the update rewrites the
        # resident arrays in place instead of copying ~cap*613B per
        # novel batch; the CPU backend does not support donation and
        # would warn per call.
        import jax

        fns = _jit_programs(jax.default_backend() != "cpu")
        self._update_fn = fns["update"]
        self._verdict_fn = fns["verdict"]
        self._reindex_fn = fns["reindex"]
        self._gather_fn = fns["gather"]
        self._lookup_fn = fns["lookup"]

    # -- host bookkeeping ---------------------------------------------------

    def _put(self, x):
        import jax

        if self._device is not None:
            return jax.device_put(x, self._device)
        return jax.device_put(x)

    def _alloc_locked(self, cap: int) -> None:
        from phant_tpu.ops.keccak_jax import INDEX_EMPTY

        self._cap = cap
        self._arrays = (
            self._put(np.zeros((cap, 8), np.uint32)),
            self._put(np.zeros((cap, 17, 8), np.uint32)),
            self._put(np.zeros((cap, 17), bool)),
            self._put(np.full((4 * cap,), INDEX_EMPTY, np.int32)),
            self._put(np.zeros((cap, 2), np.uint32)),
        )

    def _grow_locked(self, need: int) -> None:
        """Double the row space (pow2 generations) up to max_cap. The
        index is rebuilt — bucket positions depend on the table size —
        via one device program; nothing is read back."""
        import jax.numpy as jnp

        if self._arrays is None:
            cap = self._start_cap
            while cap < min(need, self._max_cap):
                cap *= 2
            self._alloc_locked(min(cap, self._max_cap))
            return
        new_cap = self._cap
        while new_cap < need and new_cap < self._max_cap:
            new_cap *= 2
        if new_cap <= self._cap:
            return
        d, r, rl, _idx, fps = self._arrays
        pad = new_cap - self._cap
        d = jnp.pad(d, ((0, pad), (0, 0)))
        r = jnp.pad(r, ((0, pad), (0, 0), (0, 0)))
        rl = jnp.pad(rl, ((0, pad), (0, 0)))
        fps = jnp.pad(fps, ((0, pad), (0, 0)))
        idx, dropped = self._reindex_fn(fps, jnp.int32(self._n_rows))
        self._deferred_dropped.append(dropped)
        self._arrays = (d, r, rl, idx, fps)
        self._cap = new_cap
        self.stats["grows"] += 1

    def flush(self) -> None:
        """Generation flush: drop every resident row AND the device
        arrays. Called by the owning engine's generation flush (host and
        device tables evict together) and by `WitnessEngine.reset()`."""
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        self._slot_of_bytes.clear()
        self._n_rows = 0
        self._cap = 0
        self._arrays = None  # releases the device buffers
        self._deferred_dropped = []
        self.generation += 1
        self.stats["flushes"] += 1

    def flush_retaining(self, nodes: Sequence[bytes]) -> None:
        """Depth-TIERED generation flush (PR 9): drop every resident row,
        then re-commit `nodes` — the owning engine's pinned shallow set,
        in ITS snapshot order — into the fresh generation. Rows restart
        at 0..len(nodes)-1 exactly like the host core's pinned re-commit,
        and the open-addressed index is rebuilt over exactly the pinned
        fingerprints, so host and device tables keep agreeing about what
        exists across a tiered flush. The device re-hashes the pinned
        bytes once per flush (the update program already fuses hash +
        ref-extract + scatter + index insert) — flush-time cost, never
        the per-batch hot path. Nodes the kernel cannot absorb, or past
        max_cap, are silently dropped from the device set: the HOST
        keeps them pinned and the prune re-uploads on next use — a perf
        miss, never an inconsistency."""
        from phant_tpu.crypto.keccak import RATE

        limit = WITNESS_MAX_CHUNKS * RATE
        with self._lock:
            self._flush_locked()
            keep = [n for n in nodes if len(n) < limit][: self._max_cap]
            if not keep:
                return
            self._grow_locked(len(keep))
            sob = self._slot_of_bytes
            for j, nb in enumerate(keep):
                sob[nb] = j
            self._n_rows = len(keep)
            raw = b"".join(keep)
            blob_len = _pow2ceil(len(raw) + WITNESS_MAX_CHUNKS * RATE)
            np_b = _pow2ceil(len(keep))
            blob = np.zeros(blob_len, np.uint8)
            blob[: len(raw)] = np.frombuffer(raw, np.uint8)
            lens = np.zeros(np_b, np.int32)
            lens[: len(keep)] = [len(nb) for nb in keep]
            offsets = np.zeros(np_b, np.int32)
            np.cumsum(lens[:-1], out=offsets[1:])
            slots = np.full(np_b, -1, np.int32)
            slots[: len(keep)] = np.arange(len(keep), dtype=np.int32)
            out = self._update_fn(
                *self._arrays,
                self._put(blob),
                self._put(offsets),
                self._put(lens),
                self._put(slots),
                max_chunks=WITNESS_MAX_CHUNKS,
            )
            self._arrays = out[:5]
            self._deferred_dropped.append(out[5])
            self.stats["uploaded_nodes"] += len(keep)
            self.stats["uploaded_bytes"] += len(raw)
            self.stats["retained_rows"] = len(keep)

    def note_index_dropped(self, n: int) -> None:
        with self._lock:
            self.stats["index_dropped"] += n

    def return_dropped(self, outs: list) -> None:
        """Give unread drop-count device scalars back (an ABANDONED
        handle never resolves them): they re-attach to the next
        dispatched batch, so `index_dropped` cannot silently undercount
        across a crash path."""
        with self._lock:
            self._deferred_dropped.extend(outs)

    def rows(self) -> int:
        with self._lock:
            return self._n_rows

    def stats_snapshot(self) -> dict:
        with self._lock:
            st = dict(self.stats)
            st["rows"] = self._n_rows
            st["cap"] = self._cap
            st["generation"] = self.generation
            return st

    def host_rows_of(self, nodes: Sequence[bytes]) -> np.ndarray:
        """(N,) int32 resident rows per the AUTHORITATIVE host map (-1 =
        not resident). Tests cross-check the device index against this."""
        with self._lock:
            return np.fromiter(
                (self._slot_of_bytes.get(n, -1) for n in nodes),
                np.int32,
                len(nodes),
            )

    def arrays(self) -> tuple:
        """The live (digests, refs, ref_live, index, fps) handles — the
        bench slope protocol and tests read them; treat as immutable."""
        with self._lock:
            if self._arrays is None:
                raise RuntimeError("resident table has no device arrays yet")
            return self._arrays

    def device_lookup(self, fps: np.ndarray) -> np.ndarray:
        """Device-side row resolution from (N, 2) u32 fingerprints — the
        on-device scan (forced sync: a test/bench surface, not the
        serving hot path)."""
        arrays = self.arrays()
        return np.asarray(self._lookup_fn(arrays[3], arrays[4], self._put(fps)))

    # -- the per-batch dispatch ---------------------------------------------

    def dispatch(
        self,
        witnesses: Sequence[Tuple[bytes, Sequence[bytes]]],
        core_novel: Sequence[bytes],
    ) -> Optional[ResidentBatch]:
        """Enqueue one resident verify batch with NO host sync: prune the
        upload against the authoritative host map, assign rows to the
        truly-novel bytes, enqueue the update (hash + ref-extract +
        scatter + index insert) and the verdict program, and hand back
        the unresolved handle. Returns None when this batch cannot go
        resident (a node past the kernel's absorb capacity, or more
        unique nodes than max_cap) — the caller falls back to the
        classic route."""
        from phant_tpu.crypto.keccak import RATE

        limit = WITNESS_MAX_CHUNKS * RATE
        with metrics.phase("witness_resident.dispatch"):
            with self._lock:
                return self._dispatch_locked(witnesses, core_novel, limit)

    def _dispatch_locked(self, witnesses, core_novel, limit: int):
        n_blocks = len(witnesses)
        if n_blocks == 0:
            return None
        all_nodes: List[bytes] = []
        counts = np.empty(n_blocks, np.int64)
        for b, (_root, nodes) in enumerate(witnesses):
            counts[b] = len(nodes)
            all_nodes.extend(nodes)
        sob = self._slot_of_bytes
        pruned = sum(1 for n in core_novel if n in sob)

        def scan_candidates() -> Optional[List[bytes]]:
            cand: List[bytes] = []
            seen = set()
            for n in all_nodes:
                if n in sob or n in seen:
                    continue
                if len(n) >= limit:
                    return None  # device kernel cannot hash this node
                seen.add(n)
                cand.append(n)
            return cand

        cand = scan_candidates()
        if cand is None:
            return None
        if self._n_rows + len(cand) > self._max_cap:
            # the resident generation is full: flush (host flushes are
            # synchronized the other way — engine flush calls ours) and
            # re-treat the whole batch as novel against the new
            # generation. A single batch larger than max_cap can never
            # go resident.
            self._flush_locked()
            cand = scan_candidates()
            if cand is None or len(cand) > self._max_cap:
                return None
        if self._arrays is None or self._n_rows + len(cand) > self._cap:
            self._grow_locked(self._n_rows + len(cand))

        h = ResidentBatch()
        h._table = self
        h.n_blocks = n_blocks
        h.generation = self.generation

        # authoritative commit: assign rows to the truly-novel bytes
        base = self._n_rows
        for j, nb in enumerate(cand):
            sob[nb] = base + j
        self._n_rows = base + len(cand)

        # update program: upload ONLY the pruned novel bytes
        if cand:
            raw = b"".join(cand)
            from phant_tpu.crypto.keccak import RATE as _RATE

            blob_len = _pow2ceil(len(raw) + WITNESS_MAX_CHUNKS * _RATE)
            np_b = _pow2ceil(len(cand))
            blob = np.zeros(blob_len, np.uint8)
            blob[: len(raw)] = np.frombuffer(raw, np.uint8)
            lens = np.zeros(np_b, np.int32)
            lens[: len(cand)] = [len(nb) for nb in cand]
            offsets = np.zeros(np_b, np.int32)
            np.cumsum(lens[:-1], out=offsets[1:])
            slots = np.full(np_b, -1, np.int32)
            slots[: len(cand)] = np.arange(base, base + len(cand), dtype=np.int32)
            out = self._update_fn(
                *self._arrays,
                self._put(blob),
                self._put(offsets),
                self._put(lens),
                self._put(slots),
                max_chunks=WITNESS_MAX_CHUNKS,
            )
            self._arrays = out[:5]
            h.dropped_outs.append(out[5])
        h.dropped_outs.extend(self._deferred_dropped)
        self._deferred_dropped = []

        # verdict program: row ids + roots only (4 B/node + 32 B/block)
        n_nodes = len(all_nodes)
        np_pad = _pow2ceil(max(n_nodes, 1))
        rows = np.full(np_pad, -1, np.int32)
        rows[:n_nodes] = np.fromiter(
            (sob[nb] for nb in all_nodes), np.int32, n_nodes
        )
        block_id = np.zeros(np_pad, np.int32)
        block_id[:n_nodes] = np.repeat(
            np.arange(n_blocks, dtype=np.int32), counts
        )
        nb_pad = _pow2ceil(n_blocks)
        roots_w = np.zeros((nb_pad, 8), np.uint32)
        for b, (root, _nodes) in enumerate(witnesses):
            roots_w[b] = np.frombuffer(root, dtype="<u4")
        digests, refs, ref_live = self._arrays[:3]
        rows_d = self._put(rows)
        h.verdict_out = self._verdict_fn(
            digests,
            refs,
            ref_live,
            rows_d,
            rows_d >= 0,
            self._put(block_id),
            self._put(roots_w),
        )

        # core-commit digests: the engine's host tables intern from the
        # DEVICE digests, so the host never hashes on this route
        h.n_core_novel = len(core_novel)
        if core_novel:
            cslots = np.full(_pow2ceil(len(core_novel)), -1, np.int32)
            cslots[: len(core_novel)] = np.fromiter(
                (sob[nb] for nb in core_novel), np.int32, len(core_novel)
            )
            h.digest_out = self._gather_fn(digests, self._put(cslots))

        h.uploaded_nodes = len(cand)
        h.uploaded_bytes = sum(map(len, cand))
        self.stats["uploaded_nodes"] += h.uploaded_nodes
        self.stats["uploaded_bytes"] += h.uploaded_bytes
        self.stats["pruned_nodes"] += pruned
        self.stats["batches"] += 1
        return h


# ---------------------------------------------------------------------------
# slope-timed chained dispatch (the RTT-insensitive steady-state rate)
# ---------------------------------------------------------------------------


def slope_time_resident(
    table: ResidentTable,
    node_fps: np.ndarray,
    node_live: np.ndarray,
    block_id: np.ndarray,
    roots_words: np.ndarray,
    *,
    k_hi: int = 65,
    reps: int = 3,
) -> float:
    """Per-iteration device seconds of the resident fused witness step,
    isolated from the link: chain k data-dependent iterations — device
    row LOOKUP from fingerprints (the on-device scan) + resident verdict
    join — inside ONE jit call and fit the slope between k=1 and k=k_hi,
    reading back a single u32. The same methodology as the keccak
    kernel's bench (_slope_time_chunked): a forced full readback per
    call measures tunnel round trips, not compute, and on a ~43 Mbps
    tunnel that floor is orders of magnitude above the actual step.

    The chained steady state uploads NOTHING per iteration (fingerprints
    ride up once); the data dependence between iterations is
    `vs // (vs + 1)` — zero at runtime for any verdict sum, but opaque
    to constant folding, so XLA must serialize the chain."""
    import time

    import jax
    import jax.numpy as jnp

    digests, refs, ref_live, index, fps = table.arrays()
    q = table._put(node_fps.astype(np.uint32))
    live = table._put(node_live.astype(bool))
    bid = table._put(block_id.astype(np.int32))
    roots = table._put(roots_words.astype(np.uint32))

    @functools.partial(jax.jit, static_argnames=("k",))
    def chain(digests, refs, ref_live, index, fps, q, live, bid, roots, k):
        def body(_i, carry):
            acc, qc = carry
            rows = _lookup_impl(index, fps, qc)
            v = _verdict_impl(digests, refs, ref_live, rows, live, bid, roots)
            vs = jnp.sum(v.astype(jnp.uint32))
            dep = vs // (vs + jnp.uint32(1))  # 0 at runtime, data-dependent
            return (acc ^ vs, qc ^ dep)

        acc, _ = jax.lax.fori_loop(0, k, body, (jnp.uint32(0), q))
        return acc

    args = (digests, refs, ref_live, index, fps, q, live, bid, roots)
    times = {}
    for k in (1, k_hi):
        np.asarray(chain(*args, k=k))  # compile + warm (bench: sync is fine)
        best = float("inf")
        for _ in range(max(reps, 1)):
            t0 = time.perf_counter()
            np.asarray(chain(*args, k=k))
            best = min(best, time.perf_counter() - t0)
        times[k] = best
    return max((times[k_hi] - times[1]) / (k_hi - 1), 1e-9)
