"""Batched keccak256 on TPU via JAX (bit-sliced, u32 lane pairs).

This is the device half of the crypto hot loop (BASELINE.md config #2):
keccak256 over thousands of variable-length payloads at once. TPUs have no
64-bit integer lanes, so each Keccak lane is a (lo, hi) pair of uint32
vectors of shape (B,); the whole f[1600] permutation is unrolled (static
rotations become shifts XLA fuses into a single elementwise program).

Variable lengths are handled host-side by padding into a fixed number of
136-byte rate chunks (`pack_payloads`); absorption of chunk c is masked per
instance by `c < nchunks`, so one compiled program serves every payload
length up to the bucket bound. Differential-tested bit-exactly against the
CPU backends (tests/test_keccak_jax.py).

Reference scope equivalence: src/crypto/hasher.zig:4-17 (scalar CPU hashing)
— the batching axis is this framework's addition per the north star.
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from phant_tpu.crypto.keccak import RATE, _KECCAK_RC as _RC

RATE_WORDS = RATE // 8  # 17 lanes absorbed per chunk

# rotation offset for lane x+5y (same table as native/keccak.cc kRot).
# A tuple, not a list: this is traced into the jitted kernels, and a
# mutable table read at trace time is a stale-closure hazard (JITHYGIENE)
_ROT = (
    0, 1, 62, 28, 27,
    36, 44, 6, 55, 20,
    3, 10, 43, 25, 39,
    41, 45, 15, 21, 8,
    18, 2, 61, 56, 14,
)


def _rotl64(lo, hi, r: int):
    """Rotate a 64-bit lane stored as (lo, hi) u32 pair by static r."""
    r %= 64
    if r == 0:
        return lo, hi
    if r == 32:
        return hi, lo
    if r < 32:
        nlo = (lo << r) | (hi >> (32 - r))
        nhi = (hi << r) | (lo >> (32 - r))
        return nlo, nhi
    r -= 32
    nlo = (hi << r) | (lo >> (32 - r))
    nhi = (lo << r) | (hi >> (32 - r))
    return nlo, nhi


_RC_LO = np.array([rc & 0xFFFFFFFF for rc in _RC], dtype=np.uint32)
_RC_HI = np.array([rc >> 32 for rc in _RC], dtype=np.uint32)


def _keccak_round(lo: List, hi: List, rc_lo, rc_hi) -> Tuple[List, List]:
    """One Keccak-f round; rotations are static, the round constant is traced."""
    # theta
    clo = [lo[x] ^ lo[x + 5] ^ lo[x + 10] ^ lo[x + 15] ^ lo[x + 20] for x in range(5)]
    chi_ = [hi[x] ^ hi[x + 5] ^ hi[x + 10] ^ hi[x + 15] ^ hi[x + 20] for x in range(5)]
    for x in range(5):
        r1lo, r1hi = _rotl64(clo[(x + 1) % 5], chi_[(x + 1) % 5], 1)
        dlo = clo[(x - 1) % 5] ^ r1lo
        dhi = chi_[(x - 1) % 5] ^ r1hi
        for y in range(5):
            lo[x + 5 * y] = lo[x + 5 * y] ^ dlo
            hi[x + 5 * y] = hi[x + 5 * y] ^ dhi
    # rho + pi
    blo = [None] * 25
    bhi = [None] * 25
    for x in range(5):
        for y in range(5):
            src = x + 5 * y
            dst = y + 5 * ((2 * x + 3 * y) % 5)
            blo[dst], bhi[dst] = _rotl64(lo[src], hi[src], _ROT[src])
    # chi
    for y in range(5):
        row_lo = [blo[x + 5 * y] for x in range(5)]
        row_hi = [bhi[x + 5 * y] for x in range(5)]
        for x in range(5):
            lo[x + 5 * y] = row_lo[x] ^ (~row_lo[(x + 1) % 5] & row_lo[(x + 2) % 5])
            hi[x + 5 * y] = row_hi[x] ^ (~row_hi[(x + 1) % 5] & row_hi[(x + 2) % 5])
    # iota
    lo[0] = lo[0] ^ rc_lo
    hi[0] = hi[0] ^ rc_hi
    return lo, hi


def keccak_f1600_loop(lo: List, hi: List) -> Tuple[List, List]:
    """f[1600] as a fori_loop over rounds (compiles 24x smaller than unrolled)."""
    rc_lo = jnp.asarray(_RC_LO)
    rc_hi = jnp.asarray(_RC_HI)

    def body(rnd, carry):
        lo_t, hi_t = carry
        nlo, nhi = _keccak_round(list(lo_t), list(hi_t), rc_lo[rnd], rc_hi[rnd])
        return (tuple(nlo), tuple(nhi))

    lo_t, hi_t = jax.lax.fori_loop(0, 24, body, (tuple(lo), tuple(hi)))
    return list(lo_t), list(hi_t)


@functools.partial(jax.jit, static_argnames=("max_chunks",))
def keccak256_chunked(words: jax.Array, nchunks: jax.Array, *, max_chunks: int) -> jax.Array:
    """Batched keccak256.

    Args:
      words: (B, max_chunks, 34) uint32 — payloads already keccak-padded and
        split into 136-byte rate chunks, little-endian u32 words.
      nchunks: (B,) int32 — number of real chunks per instance (>=1).
      max_chunks: static bucket bound.

    Returns:
      (B, 8) uint32 — digests as little-endian u32 words.
    """
    # derive the zero state from the input so it inherits the input's
    # varying-manual-axes under shard_map (a fresh constant would be
    # replicated and break the fori_loop carry typing)
    zeros = words[:, 0, 0] ^ words[:, 0, 0]
    lo = [zeros] * 25
    hi = [zeros] * 25
    for c in range(max_chunks):
        live = nchunks > c  # (B,) — instances still absorbing at chunk c
        # absorb chunk c where live
        new_lo = list(lo)
        new_hi = list(hi)
        for i in range(RATE_WORDS):
            new_lo[i] = lo[i] ^ words[:, c, 2 * i]
            new_hi[i] = hi[i] ^ words[:, c, 2 * i + 1]
        new_lo, new_hi = keccak_f1600_loop(new_lo, new_hi)
        lo = [jnp.where(live, n, o) for n, o in zip(new_lo, lo)]
        hi = [jnp.where(live, n, o) for n, o in zip(new_hi, hi)]
    out = []
    for i in range(4):
        out.append(lo[i])
        out.append(hi[i])
    return jnp.stack(out, axis=1)


def keccak256_chunked_auto(
    words: jax.Array, nchunks: jax.Array, *, max_chunks: int
) -> jax.Array:
    """Device keccak dispatch: the Pallas kernel where Mosaic runs (real
    TPU — slope-timed 44.4M hashes/s on a v5e-1, ~34x the host AVX-512
    batch and 1.25x this file's jnp program), the jnp program otherwise
    (CPU-mesh tests, interpret-less backends).  Same contract and
    bit-identical output on both paths; composes inside jit (the fused
    witness/ecrecover programs call this mid-graph)."""
    from phant_tpu.ops.keccak_pallas import keccak256_chunked_pallas, pallas_available

    if pallas_available():
        return keccak256_chunked_pallas(words, nchunks, max_chunks=max_chunks)
    return keccak256_chunked(words, nchunks, max_chunks=max_chunks)


# ---------------------------------------------------------------------------
# host-side packing
# ---------------------------------------------------------------------------


def pad_payload(data: bytes, nchunks: int) -> bytes:
    """Keccak multi-rate padding into exactly nchunks rate blocks."""
    total = nchunks * RATE
    padded = bytearray(total)
    padded[: len(data)] = data
    padded[len(data)] ^= 0x01
    padded[total - 1] ^= 0x80
    return bytes(padded)


def chunks_for_len(n: int) -> int:
    """Chunks needed for an n-byte payload (padding always adds >=1 bit)."""
    return n // RATE + 1


def pack_payloads(
    payloads: Sequence[bytes], max_chunks: int | None = None
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Pack variable-length payloads into the fixed-shape device layout.

    Returns (words (B, C, 34) u32, nchunks (B,) i32, C)."""
    B = len(payloads)
    need = [chunks_for_len(len(p)) for p in payloads]
    if max_chunks is not None:
        C = max_chunks
    else:
        # round the bucket up to a power of two so repeated ad-hoc calls hit a
        # small set of compiled shapes instead of retracing per max length
        worst = max(need, default=1)
        C = 1
        while C < worst:
            C *= 2
    if max(need, default=1) > C:
        raise ValueError(f"payload needs {max(need)} chunks > bucket bound {C}")
    from phant_tpu.utils.native import load_native

    native = load_native()
    if native is not None:
        # native C-ABI packer (the new framework's glue.c equivalent)
        buf, nchunks = native.pack_keccak(payloads, C)
    else:
        buf = np.zeros((B, C * RATE), dtype=np.uint8)
        nchunks = np.zeros((B,), dtype=np.int32)
        for i, p in enumerate(payloads):
            k = chunks_for_len(len(p))
            nchunks[i] = k
            buf[i, : k * RATE] = np.frombuffer(pad_payload(p, k), dtype=np.uint8)
    words = buf.reshape(B, C, RATE).view(np.uint32).reshape(B, C, 34)
    return words, nchunks, C


def digests_to_bytes(digests: np.ndarray) -> List[bytes]:
    """(B, 8) u32 LE words -> list of 32-byte digests."""
    arr = np.asarray(digests, dtype="<u4")
    return [arr[i].tobytes() for i in range(arr.shape[0])]


class DeviceDigests:
    """An UNRESOLVED batched-keccak dispatch: the device is (possibly
    still) computing; `resolve()` performs the host readback — the honest
    sync — and returns the digest list. The same async-dispatch shape as
    secp256k1_jax.ecrecover_batch_async: enqueue now, pay the sync later,
    so callers (the witness engine's pipelined resolve stage) overlap
    host work of batch N+1 with device compute of batch N.

    `on_resolve` (optional) runs after the readback — the witness engine
    uses it to return its staging buffers to the reuse pool only once the
    device can no longer be reading them."""

    __slots__ = ("out", "n", "on_resolve")

    def __init__(self, out, n: int, on_resolve=None):
        self.out = out  # (B, 8) u32 device array, B >= n
        self.n = n
        self.on_resolve = on_resolve

    def resolve(self) -> List[bytes]:
        from phant_tpu.utils.trace import metrics

        with metrics.phase("keccak.host_readback"):
            # the timed readback IS the honest sync (see phase name)
            digests = digests_to_bytes(np.asarray(self.out))[: self.n]  # phantlint: disable=HOSTSYNC — timed digest readback
        if self.on_resolve is not None:
            # fire ONCE: a second resolve() returning the same staging
            # lease to the pool twice would alias buffers across batches
            cb, self.on_resolve = self.on_resolve, None
            cb()
        return digests


def keccak256_batch_jax_async(
    payloads: Sequence[bytes], max_chunks: int | None = None
) -> DeviceDigests:
    """Enqueue a batched keccak on the device WITHOUT any host sync:
    returns a DeviceDigests handle whose `resolve()` pays the readback.
    `keccak256_batch_jax` is this plus an immediate resolve."""
    from phant_tpu.utils.trace import metrics

    platform = jax.default_backend()
    metrics.count("keccak.batches", backend=platform)
    metrics.count("keccak.bytes", sum(map(len, payloads)), backend=platform)
    words, nchunks, C = pack_payloads(payloads, max_chunks)
    with metrics.phase("keccak.device_dispatch"):
        out = keccak256_chunked_auto(
            jnp.asarray(words), jnp.asarray(nchunks), max_chunks=C
        )
    return DeviceDigests(out, len(payloads))


def keccak256_batch_jax(payloads: Sequence[bytes], max_chunks: int | None = None) -> List[bytes]:
    """Convenience end-to-end helper (host pack -> device hash -> bytes).

    Dispatches through keccak256_chunked_auto (Pallas on real TPUs).
    Counts batches/bytes per device platform and splits the upload+dispatch
    timer from the forced-readback timer in the metrics registry."""
    if not payloads:
        return []
    return keccak256_batch_jax_async(payloads, max_chunks).resolve()


# ---------------------------------------------------------------------------
# device-resident digest index (open addressing over digest fingerprints)
#
# The primitives behind the device-resident intern table
# (ops/witness_resident.py): a flat power-of-two bucket array maps a
# 64-bit digest FINGERPRINT (the first two little-endian digest words —
# crypto-derived, so uniformly distributed) to a resident row slot, with
# linear probing. Insertion is vectorized first-empty-claim via scatter-min
# (lowest slot id wins a contested bucket; losers retry the next probe
# position), so a whole novel batch inserts in INDEX_PROBES fused rounds
# with zero host round trips. Lookup probes the same fixed sequence and
# verifies the full 64-bit fingerprint against the per-row `fps` store —
# a miss (or a fingerprint past the probe bound) resolves to -1, which the
# resident verdict treats as NOT PRESENT (the block fails, never silently
# passes). These compose inside jit: the resident update/verdict programs
# call them mid-graph exactly like keccak256_chunked_auto.
# ---------------------------------------------------------------------------

#: bucket value marking an empty index slot. Chosen LARGE (not -1) so the
#: claim scatter can be a pure `.at[pos].min(slot)` — min(occupied, EMPTY)
#: keeps the occupant, min(EMPTY, slot) claims, and a contested bucket
#: deterministically goes to the lowest slot id.
INDEX_EMPTY = 1 << 30

#: probe-sequence bound (a fori_loop trip count). With the index sized
#: at 4x the row capacity (load factor <= 0.25; measured: 2x/16 probes
#: dropped 17 of 32k inserts — linear-probe clusters grow fast with
#: load), clusters beyond this bound are vanishingly rare; inserts that
#: exhaust it are COUNTED (dropped), and a dropped row simply misses on
#: device lookup — the host row path never depends on the index.
INDEX_PROBES = 32


def fingerprint_mix(d0: jax.Array, d1: jax.Array) -> jax.Array:
    """(N,) u32 bucket hash of a 64-bit fingerprint (murmur3 finalizer
    over the two u32 halves). Pure lane math — stays on device."""
    h = d0 ^ (d1 * jnp.uint32(0x9E3779B9))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    return h ^ (h >> 16)


def index_insert(
    index: jax.Array, new_fps: jax.Array, slots: jax.Array, live: jax.Array
):
    """Insert fingerprint->slot entries into the open-addressed index.

    index: (nslots,) int32 buckets (INDEX_EMPTY = free), nslots a power
      of two. new_fps: (N, 2) u32 fingerprints. slots: (N,) int32 row
      slots. live: (N,) bool — padding rows never insert.

    Returns (index, dropped): dropped counts rows still unplaced after
    INDEX_PROBES rounds (they stay resident by ROW — only device-side
    lookup misses them)."""
    mask = jnp.uint32(index.shape[0] - 1)
    h = fingerprint_mix(new_fps[:, 0], new_fps[:, 1])
    empty = jnp.int32(INDEX_EMPTY)

    def body(rnd, carry):
        # a fori_loop, not an unrolled Python loop: one compiled body
        # (the unrolled form made XLA chew through PROBES scatter/gather
        # rounds at trace time — minutes of compile on the CPU backend)
        index, pending = carry
        pos = ((h + rnd.astype(jnp.uint32)) & mask).astype(jnp.int32)
        cur = index[pos]
        want = pending & (cur >= empty)
        bid = jnp.where(want, slots, empty)
        index = index.at[pos].min(bid)
        won = want & (index[pos] == slots)
        return index, pending & ~won

    index, pending = jax.lax.fori_loop(0, INDEX_PROBES, body, (index, live))
    return index, pending.sum(dtype=jnp.int32)


def index_lookup(index: jax.Array, fps: jax.Array, q: jax.Array) -> jax.Array:
    """(B,) int32 resident slots for query fingerprints `q` (B, 2), or -1
    when absent. `fps` is the per-row (cap, 2) fingerprint store; a probe
    hit requires FULL 64-bit fingerprint equality, so a bucket holding a
    colliding-bucket neighbor just advances the probe."""
    cap = fps.shape[0]
    mask = jnp.uint32(index.shape[0] - 1)
    h = fingerprint_mix(q[:, 0], q[:, 1])
    empty = jnp.int32(INDEX_EMPTY)

    def body(rnd, found):
        pos = ((h + rnd.astype(jnp.uint32)) & mask).astype(jnp.int32)
        s = index[pos]
        sc = jnp.clip(s, 0, cap - 1)
        match = (s < empty) & (fps[sc, 0] == q[:, 0]) & (fps[sc, 1] == q[:, 1])
        return jnp.where((found < 0) & match, s, found)

    return jax.lax.fori_loop(
        0, INDEX_PROBES, body, jnp.full(q.shape[0], -1, jnp.int32)
    )
