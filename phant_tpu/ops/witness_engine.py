"""Memoized witness-verification engine: hash once, verify forever.

A continuously-validating stateless client sees the same trie nodes over and
over: the upper levels of the state trie change only along the paths the
previous block wrote, so consecutive block witnesses overlap heavily. The
reference client ignores this structure — it recomputes every node hash of
every block from scratch (reference scope: src/mpt/mpt.zig:38-119 recomputes
the root per block; src/crypto/hasher.zig:4-17 hashes one node at a time,
no reuse). This engine is the framework's north-star redesign of that loop:

  * every UNIQUE node byte-string is keccak-hashed exactly once, in large
    batches, on the selected crypto backend (the TPU kernel behind
    `--crypto_backend=tpu`, the native C batch otherwise);
  * digests and the parent->child hash references are interned into integer
    ids, so per-block linked-multiproof verification — "the nodes form a
    connected subtree rooted at the claimed state root" — collapses to a
    vectorized integer join (numpy sort + searchsorted), with no
    cryptography on the hot path at all;
  * the interning survives across blocks/batches, so the steady-state cost
    of validating block N is hashing the handful of nodes block N-1's
    writes actually changed.

Soundness: a digest is only ever computed from the full node bytes by the
(differential-tested) keccak backends, and ref->row resolution uses exact
256-bit digest equality via byte-keyed dicts. Memoization is sound because
keccak is a function; linking a foreign node would need a collision.
Verdict semantics are identical to ops/witness_jax.witness_verify_fused and
mpt/proof.verify_witness_linked (differential-tested in
tests/test_witness_engine.py).

Memory is bounded: `max_nodes` caps the interned set; crossing it drops the
oldest generation of interned nodes (their parents' child links are
re-resolved lazily if the same bytes are ever re-inserted).
"""

from __future__ import annotations

import itertools
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from phant_tpu.utils.trace import metrics
from phant_tpu.ops.witness_jax import (
    WITNESS_MAX_CHUNKS,
    _account_storage_root_off,
    _rlp_item_bounds,
    _scan_list_refs,
)

_NO_ROW = np.int64(-1)


class _HostStaging:
    """Reusable host staging buffers, keyed by shape bucket.

    The device hashing path pads both its axes to power-of-two buckets, so
    steady-state batches land on a handful of distinct shapes — yet every
    call used to allocate (and page-zero) a fresh padded blob. This pool
    hands the same arrays back out instead: `take(key)` pops a free entry
    (or returns None, caller allocates), `give(key, entry)` returns one
    for reuse. Entries are dicts of arrays plus whatever dirty-watermark
    the caller tracks; a borrowed entry is owned exclusively by its
    borrower until given back, so pipelined batches in flight never alias
    a buffer (each holds its own lease until its resolve stage)."""

    def __init__(self, max_free_per_key: int = 4):
        self._lock = threading.Lock()
        self._free: Dict[tuple, List[dict]] = {}
        self._max_free = max_free_per_key

    def take(self, key: tuple) -> Optional[dict]:
        with self._lock:
            entries = self._free.get(key)
            if entries:
                return entries.pop()
        return None

    def give(self, key: tuple, entry: dict) -> None:
        with self._lock:
            entries = self._free.setdefault(key, [])
            if len(entries) < self._max_free:
                entries.append(entry)


#: process-global staging pool (shapes are engine-independent)
_staging = _HostStaging()


class BatchHandle:
    """One in-flight verify batch between `begin_batch` (pack + dispatch)
    and `resolve_batch` (readback/hash + commit + linkage join). Opaque to
    callers; `resolved` flips once the verdict has been returned."""

    __slots__ = (
        "kind",         # "ext" | "native" | "python"
        "n_blocks",
        "novel",        # list[bytes] to hash (empty: fully cached batch)
        "n_novel",      # len(novel), preserved after resolve clears the list
        "miss",
        "total",
        "ext_batch",    # ext core: the pyext Batch object
        "rows",         # native/python cores: scan rows
        "novel_idx",    # native core
        "joined",       # native core: pins the packed blob
        "blob",
        "offsets",
        "lens",
        "pack_entry",   # native core: staging entry to return at resolve
        "counts",       # per-block node counts (verdict composition)
        "roots",        # concatenated roots (native) / witness list (python)
        "witnesses",    # python core linkage join
        "device",       # keccak_jax.DeviceDigests when dispatched async
        "resident",     # witness_resident.ResidentBatch on the resident route
        "ref_hint",     # python core: prefetch-decoded bytes -> child refs
        "resolved",
    )

    def __init__(self):
        for name in self.__slots__:
            setattr(self, name, None)
        self.novel = []
        self.resolved = False


class _DepthStats:
    """`cache_hit_rate vs trie_depth` (PHANT_DEPTH_HIST=1): classify every
    witness-node occurrence by its depth under its block's root and by
    novelty, publishing the `witness_engine.depth_hits{depth=}` /
    `depth_misses{depth=}` counter families — the /metrics surface that
    validates the Patricia-trie reuse model (PAPERS.md 2408.14217: node
    reuse is heavy and DEPTH-SKEWED; top-of-trie nodes should hit ~always,
    leaf-level nodes carry the misses) against live traffic, and the
    measurement the resident-table eviction policy leans on.

    Depth needs node digests (parent->child links ARE digests), so the
    helper keeps its own bytes -> (digest, child-ref digests) memo: a
    never-seen node is C-hashed once HERE, and the steady state is pure
    dict lookups plus a per-block BFS from the root. Classification: the
    FIRST occurrence of never-memoized bytes is the MISS; every later
    occurrence — same batch or later — is a hit, matching the engine's
    unique-novel accounting (`cache_misses` = unique novel count, PR 5).
    The memo flushes together with the engine's generation flushes.
    Depth labels are bounded: "0".."6", "7+", and "u" for nodes
    unreachable from the root (an unlinked witness — those blocks fail
    verification anyway)."""

    def __init__(self, max_nodes: int):
        self._memo: Dict[bytes, tuple] = {}
        self._max = max(max_nodes, 1024)
        self._lock = threading.Lock()

    def flush(self) -> None:
        with self._lock:
            self._memo.clear()

    def record(self, witnesses) -> None:
        hits: Dict[str, int] = {}
        misses: Dict[str, int] = {}
        with self._lock:
            memo = self._memo
            fresh: List[bytes] = []
            seen = set()
            for _root, nodes in witnesses:
                for n in nodes:
                    if n not in memo and n not in seen:
                        seen.add(n)
                        fresh.append(n)
            if fresh and len(memo) + len(fresh) > self._max:
                # bounded like the engine tables — and the clear must
                # RE-SCAN: the batch's previously-memoized (hit) nodes
                # are gone too, and the BFS below reads memo[n] for
                # every node, so they must re-enter as fresh (their
                # occurrences count as misses, exactly like an engine
                # generation flush)
                memo.clear()
                fresh = []
                seen = set()
                for _root, nodes in witnesses:
                    for n in nodes:
                        if n not in seen:
                            seen.add(n)
                            fresh.append(n)
            if fresh:
                from phant_tpu.utils.native import load_native

                native = load_native()
                if native is not None:
                    digests = list(native.keccak256_batch_fast(fresh))
                else:
                    from phant_tpu.crypto.keccak import keccak256

                    digests = [keccak256(n) for n in fresh]
                for n, dg in zip(fresh, digests):
                    memo[n] = (dg, tuple(_extract_ref_digests(n)))
            consumed: set = set()  # fresh bytes whose one miss was counted
            for root, nodes in witnesses:
                infos = [memo[n] for n in nodes]
                by_digest: Dict[bytes, list] = {}
                for i, (dg, _refs) in enumerate(infos):
                    by_digest.setdefault(dg, []).append(i)
                depth = [-1] * len(nodes)
                frontier = list(by_digest.get(root, ()))
                for i in frontier:
                    depth[i] = 0
                d = 0
                while frontier:
                    nxt: List[int] = []
                    for i in frontier:
                        for r in infos[i][1]:
                            for j in by_digest.get(r, ()):
                                if depth[j] < 0:
                                    depth[j] = d + 1
                                    nxt.append(j)
                    frontier = nxt
                    d += 1
                for i, n in enumerate(nodes):
                    if depth[i] < 0:
                        lbl = "u"
                    elif depth[i] < 7:
                        lbl = str(depth[i])
                    else:
                        lbl = "7+"
                    if n in seen and n not in consumed:
                        consumed.add(n)
                        tgt = misses
                    else:
                        tgt = hits
                    tgt[lbl] = tgt.get(lbl, 0) + 1
        # registry publishes outside our lock (same discipline as the
        # engine: the metrics lock never nests inside ours)
        for lbl, c in hits.items():
            metrics.count("witness_engine.depth_hits", c, depth=lbl)
        for lbl, c in misses.items():
            metrics.count("witness_engine.depth_misses", c, depth=lbl)


class _PinTracker:
    """Shallow-node classifier behind depth-TIERED eviction (PR 9).

    The PR 8 depth histogram measured what PAPERS.md 2408.14217 predicts:
    cross-block reuse is depth-skewed — depth-0 nodes hit > 90%, depth 1
    > 75%, and the rate falls monotonically toward the leaves. A flat
    generation flush therefore throws away exactly the rows most likely
    to be needed again. This tracker identifies the shallow tier so the
    flush can PIN it across generations, at (near) zero hot-path cost:

      * roots are depth-0 DIGESTS by definition — noted per batch from
        the witness tuples, no hashing;
      * when a batch's novel nodes surface with their digests (every
        commit path already has both), a novel whose digest is a known
        shallow digest is pinned, and its child references (one RLP ref
        scan of that node only) become shallow digests one level deeper.

    Hit nodes cost NOTHING (no per-occurrence work — the deliberate
    contrast with the PHANT_DEPTH_HIST per-batch BFS, which stays an
    opt-in measurement tool). Classification is conservative: a shallow
    node committed before its parent's digest was known is simply not
    pinned until it next churns — a missed pin is a perf miss, never a
    correctness issue (eviction soundness never depended on WHICH rows
    survive).

    Budgets: `budget` bounds the pinned set; at flush time the snapshot
    is shallow-FIRST (per-depth allocation falls out of the live
    classification — all of depth 0, then depth 1, ... until the budget),
    because the measured hit rate is monotone in depth.

    Staleness: pins age out at FLUSH time, never on the hot path. Each
    generation records the root digests it actually served (from the
    same per-batch note_roots); the flush snapshot keeps only pins
    reachable from the last TWO generations' roots through the pinned
    nodes' own child refs (one RLP ref scan per pinned node, flush-time
    cost — two windows because a generation can be arbitrarily short
    under a novel-filler burst, and one root-less window must not kill
    a live pin). Without the prune the budget would saturate with the
    first generations' shallow nodes and a churning trie — the real
    workload — would re-commit an increasingly dead set forever."""

    __slots__ = (
        "pin_depth",
        "budget",
        "_shallow",
        "_pinned",
        "_recent_roots",
        "_prev_roots",
    )

    def __init__(self, pin_depth: int, budget: int):
        self.pin_depth = max(0, pin_depth)
        self.budget = max(1, budget)
        # digest -> min observed depth (only depths <= pin_depth kept)
        self._shallow: Dict[bytes, int] = {}
        # node bytes -> (depth, digest): the pin candidates
        self._pinned: Dict[bytes, Tuple[int, bytes]] = {}
        # root digests served in the current / previous generation: the
        # liveness evidence the flush-time prune walks from. Two windows,
        # not one — a generation can be arbitrarily short (a burst of
        # novel filler flushes back-to-back), and a pin must survive a
        # single root-less window before it counts as dead
        self._recent_roots: set = set()
        self._prev_roots: set = set()

    def _shallow_cap(self) -> int:
        # bounded advisory state: 17 refs/node over the pinned budget,
        # plus root-digest churn headroom
        return max(4096, self.budget * 17)

    def note_roots(self, roots) -> None:
        sh = self._shallow
        if len(sh) > self._shallow_cap():
            # advisory overflow: drop and rebuild from live traffic
            # (pinned entries keep their own digests)
            sh.clear()
        rr = self._recent_roots
        if len(rr) > self._shallow_cap():
            rr.clear()  # same bounded-advisory-state contract as _shallow
        for r in roots:
            if len(r) == 32:
                rr.add(r)
                if sh.get(r, 1) > 0:
                    sh[r] = 0

    def note_novel(self, novel: Sequence[bytes], digests: Sequence[bytes]) -> None:
        """Classify one commit's novel nodes. Runs pin_depth+1 passes so
        a parent and child landing in the same batch classify regardless
        of their order in the novel list (novel lists are tiny in the
        steady state — reuse makes them so)."""
        sh, pinned = self._shallow, self._pinned
        pin_depth, budget = self.pin_depth, self.budget
        for _ in range(pin_depth + 1):
            changed = False
            for nb, dg in zip(novel, digests):
                d = sh.get(dg)
                if d is None or d > pin_depth:
                    continue
                cur = pinned.get(nb)
                if cur is not None and cur[0] <= d:
                    continue
                if cur is None and len(pinned) >= budget:
                    continue  # full: only min-depth updates of existing pins
                pinned[nb] = (d, dg)
                changed = True
                if d < pin_depth and len(sh) < self._shallow_cap():
                    for r in _extract_ref_digests(nb):
                        if sh.get(r, pin_depth + 1) > d + 1:
                            sh[r] = d + 1
            if not changed:
                break

    def pinned_snapshot(self) -> List[Tuple[bytes, bytes, int]]:
        """[(node bytes, digest, depth)] shallow-first within the budget
        (ties keep insertion order — older shallow nodes first). Called
        at FLUSH time, so it first prunes stale pins and opens the next
        generation's liveness window."""
        self._prune_stale()
        items = sorted(self._pinned.items(), key=lambda kv: kv[1][0])
        return [(nb, dg, d) for nb, (d, dg) in items[: self.budget]]

    def _prune_stale(self) -> None:
        """Keep only pins reachable from a root served THIS generation,
        walking child refs through the pinned nodes themselves (depths
        re-derive along the walk). Conservative in the documented
        direction: a live deep pin whose parent never pinned is dropped
        and re-classifies when it next churns — a perf miss, never a
        correctness issue. Runs once per generation flush, never on the
        hot path."""
        pinned = self._pinned
        rr = self._recent_roots | self._prev_roots
        self._prev_roots = self._recent_roots
        self._recent_roots = set()
        if not pinned:
            return
        by_digest = {dg: nb for nb, (_d, dg) in pinned.items()}
        live: Dict[bytes, int] = {}
        frontier = [r for r in rr if r in by_digest]
        for r in frontier:
            live[r] = 0
        depth = 0
        while frontier and depth < self.pin_depth:
            nxt = []
            for dg in frontier:
                for r in _extract_ref_digests(by_digest[dg]):
                    if r in by_digest and r not in live:
                        live[r] = depth + 1
                        nxt.append(r)
            frontier = nxt
            depth += 1
        self._pinned = {
            nb: (live[dg], dg)
            for nb, (_d, dg) in pinned.items()
            if dg in live
        }

    def per_depth(self) -> Dict[int, int]:
        out: Dict[int, int] = {}
        for _nb, (d, _dg) in self._pinned.items():
            out[d] = out.get(d, 0) + 1
        return out

    def flush(self) -> None:
        self._shallow.clear()
        self._pinned.clear()
        self._recent_roots.clear()
        self._prev_roots.clear()


class PrefetchPlan:
    """Output of `WitnessEngine.prefetch_batch` — everything the PACK
    stage would otherwise compute on the serving critical path: the host
    batch assembly, an ADVISORY novelty pre-scan against the committed
    tables, the decoded child references of the candidate novels, and
    pre-filled staging leases (host pack blob / device dispatch blob).

    Staleness contract: the plan is advisory end to end. begin_batch's
    lock-held scan remains the authoritative commit — a plan whose
    candidate set no longer matches (a concurrent batch committed some
    of them, a generation flushed) is simply dropped, which costs the
    perf win and nothing else. `release()` returns unconsumed staging
    leases to the pool (idempotent; begin_batch calls it, crash paths
    may call it again)."""

    __slots__ = (
        "witnesses",
        "all_nodes",
        "counts",
        "novel",      # candidate-novel bytes (advisory, dedup'd)
        "refs",       # python core: node bytes -> child-ref digests
        "pack_lease",  # native core: (key, entry) from _pack_entry
        "packed",      # native core: (joined, blob, offsets, lens)
        "device_lease",  # device route: filled staging from _stage_device_blob
    )

    def __init__(self):
        for name in self.__slots__:
            setattr(self, name, None)

    def release(self) -> None:
        """Return unconsumed staging leases to the pool (idempotent)."""
        if self.pack_lease is not None:
            key, entry = self.pack_lease
            self.pack_lease = self.packed = None
            _staging.give(key, entry)
        if self.device_lease is not None:
            key, entry = self.device_lease[0], self.device_lease[1]
            self.device_lease = None
            _staging.give(key, entry)


def _extract_ref_digests(node: bytes) -> List[bytes]:
    """The 32-byte child hash references of one RLP trie node (branch
    children, extension child, account-leaf storage root). Malformed nodes
    reference nothing (they can still BE referenced — same contract as the
    device kernel's _extract_ref_positions)."""
    try:
        mv = memoryview(node)
        kind, ps, pe, pos = _rlp_item_bounds(mv, len(node), 0)
        if kind != 1 or pos != len(node):
            return []
        offs: List[int] = []
        _scan_list_refs(mv, ps, pe, offs)
        return [node[o : o + 32] for o in offs]
    except (ValueError, IndexError):  # IndexError: zero-length node bytes
        return []


class WitnessEngine:
    """Cross-block memoized linked-multiproof verifier.

    One instance owns an interning table (digest <-> integer row) plus the
    resolved child-link graph; `verify_batch` verifies whole batches of
    (root, nodes) block witnesses against it.
    """

    def __init__(
        self,
        hasher: Optional[object] = None,
        max_nodes: int = 1 << 20,
        device_batch_floor: int = -1,
        device_index: Optional[int] = None,
        resident: Optional[bool] = None,
        resident_cap: Optional[int] = None,
        depth_hist: Optional[bool] = None,
        tiered_evict: Optional[bool] = None,
        pin_depth: Optional[int] = None,
        pin_budget: Optional[int] = None,
    ):
        """device_batch_floor: minimum novel-batch size that goes to the
        device hasher under `--crypto_backend=tpu`. -1 (default) = adaptive:
        measure the host->device link once and engage the device only when
        the cost model says a batch beats the native path — a tunneled chip
        (~20 MB/s) never qualifies for byte-dense hashing, a locally
        attached one (~GB/s) qualifies from a few thousand nodes up. This
        is the mechanism behind round-2's "never slower than cpu" demand:
        the flag routes by measured cost, not by hope.

        device_index: pin this engine's device hashing to ONE mesh device
        (`jax.devices()[device_index]`, resolved lazily so construction
        never imports jax). The mesh serving pool (serving/mesh_exec.py)
        gives each executor its own pinned engine: the engine's intern
        table and its device dispatches stay on the same chip, so
        bucket-affinity routing preserves the cross-block reuse the table
        exists for. A pinned engine never takes the mesh-sharded hashing
        path — sharding across the mesh is the POOL's axis, not one
        engine's.

        resident: route verdicts through a DEVICE-RESIDENT intern table
        (ops/witness_resident.py) — digest/ref rows persist on the chip
        across batches, only truly-novel bytes are uploaded, the linkage
        join runs on device, and the host tables commit from the device
        digests. None (default) = auto: engaged under
        `--crypto_backend=tpu` on a real accelerator (PHANT_RESIDENT=1
        forces it — the XLA-CPU test/proxy path — and =0 disables).
        True/False override the env. The per-batch offload cost model is
        deliberately NOT consulted on this route: residency amortizes
        each upload across every future batch, which is exactly what a
        per-batch model cannot see (the ROADMAP tunnel lesson).

        resident_cap: row bound of the resident table (default
        min(max_nodes, PHANT_RESIDENT_CAP)); it grows toward the bound
        in pow2 generations and flushes with the host generation.

        depth_hist: record the `cache_hit_rate vs trie_depth` histogram
        (witness_engine.depth_{hits,misses}{depth=}) on every batch.
        None = PHANT_DEPTH_HIST (default off: first sight of a node
        costs one extra host hash for the depth memo).

        tiered_evict: depth-TIERED generation eviction (PR 9, default
        ON; PHANT_TIERED_EVICT=0 disables). A generation flush pins the
        shallow tier (depth <= pin_depth, the near-100%-hit rows per
        the PR 8 histogram) by re-committing it into the fresh
        generation with its remembered digests — zero re-hashing —
        while deeper tiers evict generationally; the device-resident
        table re-commits the same set so host and device stay in
        lockstep. Classification is the zero-hot-path-cost _PinTracker
        (roots are depth 0 by definition; novel nodes classify when
        their digests surface at commit). On the ext core, tiering
        routes novel hashing through the Python-visible batch keccak
        instead of the in-C finish_native fast path so digests surface
        — same C hashing, one extra round trip, novel counts go to ~0
        in the steady state.

        pin_depth: deepest tier pinned across flushes (default
        PHANT_PIN_DEPTH=2 — the histogram's near-100%-hit depths).

        pin_budget: pinned-set row bound (default PHANT_PIN_BUDGET or
        max_nodes // 8); at flush time pins allocate shallow-first from
        the live classification until the budget (or the room the
        incoming batch needs) is exhausted."""
        # native C++ core (native/engine.cc): same interning + verdict
        # semantics, ~5-10x the steady-state throughput (no Python dict
        # re-hash of node bytes, no numpy sort in the join). Preferred
        # driver is the CPython extension (native/pyext.cc — feeds the
        # core scattered PyBytes pointers, zero joins); the ctypes+numpy
        # driver is the fallback (PHANT_ENGINE_EXT=0 forces it). The
        # Python tables below stay as the final fallback/differential
        # twin (PHANT_ENGINE_NATIVE=0 forces it; tests run all three).
        self._core = None
        self._ext_core = None
        if os.environ.get("PHANT_ENGINE_NATIVE", "1") == "1":
            from phant_tpu.utils.native import load_engine_ext, load_native

            ext = load_engine_ext()
            if ext is not None:
                self._ext_core = ext.Engine()
            else:
                native = load_native()
                if native is not None:
                    self._core = native.new_engine()
        # node bytes -> row (the memoization key: raw bytes, no hashing
        # needed to test membership)
        self._row_of_bytes: Dict[bytes, int] = {}
        # digest bytes -> refid. EVERY 32-byte digest that appears — as a
        # node's hash or inside a node as a child reference — gets one id,
        # so parent->child linkage resolves at insert time with no pending
        # table (an unresolved-ref table would grow with every off-path
        # sibling digest, ~16x the node count, and those digests never
        # arrive as nodes).
        self._refid_of_digest: Dict[bytes, int] = {}
        self._n_refids = 0
        # growable per-row tables
        cap = 1024
        self._own_refid = np.full(cap, _NO_ROW, np.int64)
        self._child_refids = np.full((cap, 17), _NO_ROW, np.int64)
        self._n_rows = 0
        self._max_nodes = max_nodes
        self._hasher = hasher  # callable: List[bytes] -> List[bytes]
        self._device_batch_floor = device_batch_floor
        # mesh pinning: the target index plus the lazily-resolved jax
        # device handle (write-once from whatever thread first routes to
        # the device; both writers compute the same value, so the benign
        # race needs no lock — and the engine lock must NOT be held across
        # a jax import anyway)
        self._device_index = device_index
        self._pinned = None
        self._lock = threading.Lock()  # Engine API serves from threads
        # pipelined two-phase state (begin_batch/resolve_batch), all
        # guarded by _lock: the in-flight handle count and the deferred-
        # eviction flag (a generation flush must never run while a
        # scanned-but-uncommitted batch holds row ids — the tables it
        # scanned against would vanish under it). _drained signals the
        # count hitting zero, so an over-cap begin under SUSTAINED
        # pipelined load can wait for a flush window instead of deferring
        # forever (tables are append-only and commits re-check membership,
        # so handles may begin/resolve in ANY interleaving — several
        # schedulers can share one engine)
        self._inflight = 0
        self._drained = threading.Condition(self._lock)
        self._evict_pending = False
        # the python twin tables have their OWN deferred flag: on a
        # C-core engine the public intern() fills _row_of_bytes, and its
        # overflow must flush those dicts — not the warm memoized core
        self._evict_pending_py = False
        # device-resident intern table (ops/witness_resident.py): built
        # lazily on the first resident-routed batch, behind its own init
        # lock (construction imports jax — the engine lock must not be
        # held across that)
        self._resident = None
        self._resident_opt = resident
        self._resident_cap = resident_cap
        self._resident_lock = threading.Lock()
        if depth_hist is None:
            depth_hist = os.environ.get("PHANT_DEPTH_HIST", "0") == "1"
        self._depth = _DepthStats(max_nodes) if depth_hist else None
        # depth-tiered eviction (PR 9): the shallow-node pin tracker plus
        # an ADVISORY committed-bytes set for the prefetch pre-scan. Both
        # are engine-lock-guarded at every write; the pre-scan reads the
        # set without the lock (GIL-atomic membership, re-checked by the
        # authoritative pack-time scan).
        if tiered_evict is None:
            tiered_evict = os.environ.get("PHANT_TIERED_EVICT", "1") not in (
                "0",
                "",
            )
        if pin_depth is None:
            pin_depth = int(os.environ.get("PHANT_PIN_DEPTH", "2"))
        if pin_budget is None:
            pin_budget = int(
                os.environ.get("PHANT_PIN_BUDGET", str(max(1, max_nodes // 8)))
            )
        self._pin = _PinTracker(pin_depth, pin_budget) if tiered_evict else None
        # the prefetch pre-scan's lock-free membership probe. The C cores
        # keep their committed bytes in native memory, so this is the only
        # host-side bytes-keyed view of the tables — which is exactly why
        # it must stay LAZY: it duplicates up to max_nodes of node bytes,
        # and an engine that never serves a prefetch consumer (depth-1
        # scheduler, --sched-prefetch 0, offline verify_batch) must not
        # pay that. _advisory_add is a no-op until the first
        # prefetch_batch call activates it (python core: seeded exactly
        # from _row_of_bytes; C cores: warms with subsequent commits — a
        # cold start under-reports hits, a perf miss the authoritative
        # pack-time scan absorbs).
        self._seen_advisory: set = set()
        self._advisory_active = False
        self.stats = {"hashed": 0, "hits": 0, "evictions": 0}

    # -- hashing backends ---------------------------------------------------

    def _hash_batch(
        self, nodes: List[bytes], route_device: Optional[bool] = None
    ) -> List[bytes]:
        with metrics.phase("witness_engine.hash"):
            return self._hash_batch_routed(nodes, route_device)

    def _hash_batch_routed(
        self, nodes: List[bytes], route_device: Optional[bool] = None
    ) -> List[bytes]:
        digests, backend = self._hash_novel(nodes, route_device)
        if backend in ("device", "native"):
            key = backend + "_batches"
            self.stats[key] = self.stats.get(key, 0) + 1
        return digests

    def _hash_novel(
        self, nodes: List[bytes], route_device: Optional[bool] = None
    ) -> Tuple[List[bytes], str]:
        """(digests, backend) with NO stats mutation — the pipelined
        resolve stage hashes outside the engine lock and must account the
        batch counter under it afterwards (a lock-free stats bump here
        would race concurrent callers)."""
        if self._hasher is not None:
            return list(self._hasher(nodes)), "hasher"
        if route_device is None:
            route_device = self._device_route_wanted(nodes)
        if route_device:
            try:
                return (
                    self._device_dispatch(nodes, self._pinned_device()).resolve(),
                    "device",
                )
            except Exception:
                import logging

                logging.getLogger("phant.witness").warning(
                    "device keccak failed for %d nodes; native fallback",
                    len(nodes),
                    exc_info=True,
                )
        from phant_tpu.utils.native import load_native

        native = load_native()
        if native is not None:
            return list(native.keccak256_batch_fast(nodes)), "native"
        from phant_tpu.crypto.keccak import keccak256

        return [keccak256(n) for n in nodes], "native"

    def _pinned_device(self):
        """The jax device this engine is pinned to (device_index), or None
        for default placement. Resolved lazily ON the device route — the
        only path that may import jax — and memoized; indexes past the
        device count wrap, so an 8-executor pool degrades gracefully on a
        smaller mesh."""
        if self._device_index is None:
            return None
        if self._pinned is None:
            import jax

            devices = jax.devices()
            self._pinned = devices[self._device_index % len(devices)]
        return self._pinned

    # -- device-resident intern table (ops/witness_resident.py) --------------

    def _resident_wanted(self) -> bool:
        """Route this engine's verdicts through the device-resident
        table? Auto-on under `--crypto_backend=tpu` on a real
        accelerator; PHANT_RESIDENT=1 forces (XLA-CPU tests/proxy), =0
        disables; the constructor arg overrides the env. A bench hasher
        override always wins — its batches must surface to the host
        hashing route."""
        if self._hasher is not None or self._resident_opt is False:
            return False
        env = os.environ.get("PHANT_RESIDENT", "auto")
        if env in ("0", "off") and self._resident_opt is not True:
            return False
        from phant_tpu.backend import crypto_backend, jax_device_ok

        if crypto_backend() != "tpu" or not jax_device_ok():
            return False
        if self._resident_opt is True or env == "1":
            return True
        try:
            import jax

            return jax.default_backend() != "cpu"
        except Exception:
            return False

    def _resident_table(self):
        """The engine's ResidentTable, built on first use (pinned to the
        engine's device on a mesh lane — one independent table per chip).
        Construction is serialized by `_resident_lock` and happens
        OUTSIDE the engine lock (it imports jax); the handle itself is
        engine-lock-guarded like every other table reference."""
        with self._lock:
            res = self._resident
        if res is not None:
            return res
        with self._resident_lock:
            with self._lock:
                res = self._resident
            if res is not None:
                return res
            from phant_tpu.ops.witness_resident import (
                ResidentTable,
                resident_default_cap,
            )

            table = ResidentTable(
                max_cap=self._resident_cap
                or min(self._max_nodes, resident_default_cap()),
                device=self._pinned_device(),
            )
            with self._lock:
                self._resident = table
            return table

    def _resident_dispatch(self, witnesses, novel):
        """Enqueue the resident update + verdict for one batch; None =
        this batch cannot go resident (oversized node, table failure —
        the table is dropped on failure so a dead tunnel degrades to the
        classic route instead of wedging every batch)."""
        try:
            return self._resident_table().dispatch(witnesses, novel)
        except Exception:
            import logging

            logging.getLogger("phant.witness").warning(
                "resident dispatch failed; dropping the device table and "
                "falling back to the classic route",
                exc_info=True,
            )
            with self._lock:
                self._resident = None
            return None

    def reset(self) -> None:
        """Release EVERYTHING: host tables (all cores), the python
        twins, the device-resident arrays, and the depth memo. The bench
        and soak use this between timed passes — constructing a fresh
        engine resets the HOST state, but with residency the old
        engine's device arrays would linger until GC, so pass 2 could
        silently measure a warm resident table (or accumulate device
        memory). Requires an idle pipeline (no in-flight handles)."""
        with self._lock:
            if self._inflight:
                raise RuntimeError("reset() with in-flight batch handles")
            if self._ext_core is not None:
                self._ext_core.flush()
            elif self._core is not None:
                self._core.flush()
            self._row_of_bytes.clear()
            self._refid_of_digest.clear()
            self._n_rows = 0
            self._n_refids = 0
            self._evict_pending = False
            self._evict_pending_py = False
            self._seen_advisory.clear()
            if self._pin is not None:
                self._pin.flush()
            self.stats["resets"] = self.stats.get("resets", 0) + 1
            res, self._resident = self._resident, None
        if res is not None:
            res.flush()  # drop the device arrays deterministically
        if self._depth is not None:
            self._depth.flush()

    def _flush_attached_locked(self, pinned: Sequence[tuple] = ()) -> None:
        """Flush the device-resident table and the depth memo together
        with a host GENERATION flush (caller holds the engine lock with
        an empty pipeline): host and device tables evict in lockstep, so
        they never disagree about what exists. With a tiered flush the
        resident table re-commits the same `pinned` set the host just
        retained — row ids restart together, the open-addressed index is
        rebuilt over exactly the pinned fingerprints, and the two tables
        keep agreeing about what exists. The python-TWIN-only flush
        (`_evict_pending_py`) deliberately does not come here — the core
        (and its resident mirror) stay warm there."""
        if self._resident is not None:
            if pinned:
                self._resident.flush_retaining([nb for nb, _dg, _d in pinned])
            else:
                self._resident.flush()
        if self._depth is not None:
            self._depth.flush()

    @staticmethod
    def _stage_device_blob(nodes: List[bytes]) -> tuple:
        """Lease + fill the pow2-bucketed device staging for one novel
        set: (key, entry, n_nodes) — the host-side half of a device
        dispatch, split out so the PREFETCH stage can run it off the
        serving critical path (PrefetchPlan.device_lease). Raises
        ValueError for a node past the kernel's absorb capacity, same
        contract as the dispatch itself."""
        from phant_tpu.crypto.keccak import RATE
        from phant_tpu.ops.witness_jax import _pow2ceil

        limit = WITNESS_MAX_CHUNKS * RATE
        for n in nodes:
            if len(n) >= limit:
                raise ValueError(
                    f"node of {len(n)}B exceeds device absorb capacity "
                    f"({limit}B); route to the native hasher"
                )
        raw = b"".join(nodes)
        blob_len = _pow2ceil(len(raw) + WITNESS_MAX_CHUNKS * RATE)
        B = _pow2ceil(len(nodes))
        key = ("device_blob", blob_len, B)
        entry = _staging.take(key)
        if entry is None:
            entry = {
                "blob": np.zeros(blob_len, np.uint8),
                "lens": np.zeros(B, np.int32),
                "offsets": np.zeros(B, np.int32),
                "blob_dirty": 0,
                "lens_dirty": 0,
            }
        blob, lens, offsets = entry["blob"], entry["lens"], entry["offsets"]
        # zero only the reused region past this batch's payload (a fresh
        # allocation is already zero; the pool tracks the high-water mark)
        if entry["blob_dirty"] > len(raw):
            blob[len(raw) : entry["blob_dirty"]] = 0
        if entry["lens_dirty"] > len(nodes):
            lens[len(nodes) : entry["lens_dirty"]] = 0
        blob[: len(raw)] = np.frombuffer(raw, np.uint8)
        lens[: len(nodes)] = [len(n) for n in nodes]
        entry["blob_dirty"] = len(raw)
        entry["lens_dirty"] = len(nodes)
        offsets[0] = 0
        np.cumsum(lens[:-1], out=offsets[1:])
        return (key, entry, len(nodes))

    @staticmethod
    def _device_dispatch(nodes: List[bytes], device=None, staged=None):
        """Enqueue one fused device dispatch of the concatenated novel
        bytes WITHOUT any host sync: returns a keccak_jax.DeviceDigests
        handle whose `resolve()` pays the readback. The transfer is the
        novel bytes + 2B/node — the memoized design makes this the ONLY
        recurring h2d traffic of witness verification. Both the node axis
        AND the blob byte axis are padded to power-of-two buckets so
        repeat calls hit a small set of compiled shapes (a ragged blob
        length would recompile per call) — and the padded staging arrays
        themselves are leased from `_staging` keyed by that same bucket,
        so steady-state batches stop reallocating (and page-zeroing) the
        blob every call. The lease returns to the pool on resolve, when
        the device can no longer be reading the buffers.

        `device` pins the dispatch: inputs are device_put-committed to
        that one device (jax places the compute with them) and the
        mesh-sharded route is skipped — a pinned engine is one lane of
        the serving pool's mesh, never a whole-mesh dispatcher.

        `staged` hands in a pre-filled lease from `_stage_device_blob`
        (the prefetch stage's output for exactly these nodes); ownership
        transfers here — the lease returns to the pool on resolve, or
        right away if the enqueue fails."""
        import jax.numpy as jnp

        from phant_tpu.ops.keccak_jax import DeviceDigests
        from phant_tpu.ops.witness_jax import witness_digests

        if staged is None:
            staged = WitnessEngine._stage_device_blob(nodes)
        key, entry, _n = staged
        blob, lens, offsets = entry["blob"], entry["lens"], entry["offsets"]
        B = len(lens)
        import os

        import jax

        sharded = os.environ.get("PHANT_ENGINE_SHARDED", "auto")
        if device is not None:
            # pinned engines never shard: the mesh axis belongs to the
            # serving pool (one pinned engine per device), and a pinned
            # dispatch sharding back across the mesh would defeat the
            # per-device intern-table affinity the pool routes for
            use_sharded = False
        elif sharded == "auto":
            # default ON with >1 REAL accelerator (the production
            # multi-chip topology); the virtual CPU test mesh stays
            # single-device unless explicitly opted in — its 8 "devices"
            # share one core, so sharding there only costs compiles
            use_sharded = (
                len(jax.devices()) > 1
                and jax.default_backend() != "cpu"
            )
        else:
            use_sharded = sharded == "1"
        # dispatch (upload + kernel launch) vs readback (the honest sync)
        # timed separately: on a tunneled chip the split localizes whether
        # the link or the kernel is eating the batch budget
        try:
            with metrics.phase("keccak.device_dispatch"):
                if use_sharded and len(jax.devices()) > 1 and B % len(jax.devices()) == 0:
                    # multi-chip novelty hashing: shard the node axis over
                    # the mesh (default-safe: the sharded compile's cache-
                    # suspension window is lock-serialized, parallel/mesh.py)
                    from phant_tpu.parallel.mesh import (
                        make_mesh,
                        witness_digests_sharded,
                    )

                    out = witness_digests_sharded(
                        make_mesh(),
                        blob,
                        offsets,
                        lens,
                        max_chunks=WITNESS_MAX_CHUNKS,
                    )
                elif device is not None:
                    # committed inputs pin the compute with them: the
                    # upload AND the keccak land on this engine's device
                    out = witness_digests(
                        jax.device_put(blob, device),
                        jax.device_put(offsets, device),
                        jax.device_put(lens, device),
                        max_chunks=WITNESS_MAX_CHUNKS,
                    )
                else:
                    out = witness_digests(
                        jnp.asarray(blob),
                        jnp.asarray(offsets),
                        jnp.asarray(lens),
                        max_chunks=WITNESS_MAX_CHUNKS,
                    )
        except BaseException:
            # a failed enqueue (dead tunnel) must not strand the lease —
            # the caller falls back to the native route and the buffers
            # go back to the pool
            _staging.give(key, entry)
            raise
        return DeviceDigests(
            out, len(nodes), on_resolve=lambda: _staging.give(key, entry)
        )

    @staticmethod
    def _hash_batch_device(nodes: List[bytes]) -> List[bytes]:
        """Synchronous device hashing on the DEFAULT device: dispatch +
        immediate readback (the pipelined path keeps the DeviceDigests
        handle unresolved instead so batch N+1 packs while batch N
        computes; pinned engines pass their device explicitly)."""
        return WitnessEngine._device_dispatch(nodes).resolve()

    @staticmethod
    def _pack_blob(nodes: Sequence[bytes], entry: Optional[dict] = None):
        """(joined, blob u8, offsets u64, lens u32) C-ABI layout of a node
        batch. `joined` must stay referenced while the views are in use.
        With a staging `entry` (from `_pack_entry`), the offsets array is
        a view into a pooled buffer instead of a fresh allocation — the
        caller owns the entry until the views are dead."""
        n = len(nodes)
        joined = b"".join(nodes)
        blob = np.frombuffer(joined, np.uint8)
        lens = np.fromiter(map(len, nodes), np.uint32, n)
        if entry is not None and len(entry["offsets"]) >= n:
            offsets = entry["offsets"][:n]
            offsets[0:1] = 0
        else:
            offsets = np.zeros(n, np.uint64)
        if n > 1:
            np.cumsum(lens[:-1], dtype=np.uint64, out=offsets[1:])
        return joined, blob, offsets, lens

    @staticmethod
    def _pack_entry(n: int) -> Tuple[tuple, dict]:
        """Lease a `_pack_blob` staging entry sized for `n` nodes (pow2
        bucket). Return it with `_staging.give(key, entry)` once the blob
        views are no longer referenced."""
        from phant_tpu.ops.witness_jax import _pow2ceil

        cap = _pow2ceil(max(n, 1))
        key = ("pack_offsets", cap)
        entry = _staging.take(key)
        if entry is None:
            entry = {"offsets": np.zeros(cap, np.uint64)}
        return key, entry

    @staticmethod
    def _refs_for_batch(nodes: List[bytes]) -> Tuple[List[bytes], np.ndarray]:
        """(ref_digests, ref_node): the flat scan-order list of 32-byte
        child references across the whole batch plus each ref's node index
        (non-decreasing — scan order). Batched through the native C scanner
        when available; malformed nodes — which the native scanner rejects
        wholesale — fall back to the per-node Python walk that marks just
        the bad ones ref-less."""
        from phant_tpu.utils.native import load_native

        native = load_native()
        if native is not None:
            raw, blob, offsets, lens = WitnessEngine._pack_blob(nodes)
            try:
                ref_off, ref_node = native.scan_refs(blob, offsets, lens)
            except ValueError:
                pass
            else:
                refs = [raw[o : o + 32] for o in ref_off.tolist()]
                return refs, ref_node.astype(np.int64)
        refs = []
        ref_node_l = []
        for i, nb in enumerate(nodes):
            for r in _extract_ref_digests(nb):
                refs.append(r)
                ref_node_l.append(i)
        return refs, np.asarray(ref_node_l, np.int64)

    # -- interning ----------------------------------------------------------

    def _grow(self, need: int) -> None:
        cap = self._own_refid.shape[0]
        if need <= cap:
            return
        new_cap = cap
        while new_cap < need:
            new_cap *= 2
        o = np.full(new_cap, _NO_ROW, np.int64)
        o[:cap] = self._own_refid
        c = np.full((new_cap, 17), _NO_ROW, np.int64)
        c[:cap] = self._child_refids
        self._own_refid, self._child_refids = o, c

    def _evict_all(self) -> None:
        """Generation flush: drop the whole interned set and start ids over.
        Safe because nothing outside the (just-cleared) dicts holds row or
        ref ids, and every insert fully re-initializes its per-row entries."""
        self.stats["evictions"] += 1
        self._row_of_bytes.clear()
        self._refid_of_digest.clear()
        self._n_rows = 0
        self._n_refids = 0

    def intern(self, nodes: Sequence[bytes]) -> np.ndarray:
        """Public interning entry point — takes the engine lock.

        `verify_batch` reaches the same table through `_intern_locked`
        (it already holds the lock; threading.Lock does not re-enter), so
        direct callers — tests, warm-up loops — get the same mutual
        exclusion the serving path has instead of racing it (phantlint
        LOCK: every `stats`/table touch outside the lock was a finding)."""
        with self._lock:
            return self._intern_locked(nodes)

    def _scan_rows_locked(
        self, nodes: Sequence[bytes]
    ) -> Tuple[np.ndarray, List[bytes], int]:
        """(rows, novel, miss): the pure hit scan — NO table mutation, so
        the pipelined pack stage can run it while earlier batches are
        still uncommitted. rows[i] is a row id, or -2-k pointing into the
        novel first-occurrence list; miss counts every negative entry
        (novel duplicates included), the `hits` complement."""
        # bulk hit scan: one C-level map over the interning dict instead of
        # a Python loop with per-node numpy scalar writes — the steady
        # state is ~all hits, so this IS the verification hot path
        n = len(nodes)
        rows = np.fromiter(
            map(self._row_of_bytes.get, nodes, itertools.repeat(-1)),
            np.int64,
            n,
        )
        miss_idx = np.nonzero(rows < 0)[0]
        novel: List[bytes] = []
        seen_this_call: Dict[bytes, int] = {}
        for i in miss_idx.tolist():
            nb = nodes[i]
            j = seen_this_call.get(nb)
            if j is not None:
                rows[i] = -2 - j  # forward ref into this call's novel list
                continue
            seen_this_call[nb] = len(novel)
            rows[i] = -2 - len(novel)
            novel.append(nb)
        return rows, novel, len(miss_idx)

    def _commit_novel_locked(
        self,
        rows: np.ndarray,
        novel: List[bytes],
        digests: List[bytes],
        ref_hint: Optional[Dict[bytes, list]] = None,
    ) -> None:
        """Insert `novel` (with caller-computed digests), intern every
        digest + child reference, and patch the negative entries of `rows`
        in place. Caller holds `self._lock`.

        Each novel node's digest AND each of its child-reference digests
        are interned to refids immediately, so linkage is fully resolved
        at insert: a parent cached today links to a child that first
        arrives as a node next week, because both map to the same refid.

        A novel entry already present in the table — committed by an
        earlier in-flight pipelined batch between this batch's scan and
        now — reuses the existing row instead of inserting a duplicate."""
        row_of_bytes = self._row_of_bytes
        actual = np.empty(len(novel), np.int64)
        fresh_idx: List[int] = []
        for k, nb in enumerate(novel):
            got = row_of_bytes.get(nb)
            if got is None:
                fresh_idx.append(k)
            else:
                actual[k] = got
        if len(fresh_idx) == len(novel):
            fresh, fresh_digests = novel, digests
        else:
            fresh = [novel[k] for k in fresh_idx]
            fresh_digests = [digests[k] for k in fresh_idx]

        if fresh:
            if ref_hint is not None and all(nb in ref_hint for nb in fresh):
                # prefetch already RLP-decoded these nodes' child refs
                # (content-derived: bytes -> refs can never go stale, it
                # can only go unused when the hint misses a fresh node)
                ref_digests = []
                ref_node_l: List[int] = []
                for i, nb in enumerate(fresh):
                    for r in ref_hint[nb]:
                        ref_digests.append(r)
                        ref_node_l.append(i)
                ref_node = np.asarray(ref_node_l, np.int64)
            else:
                ref_digests, ref_node = self._refs_for_batch(fresh)
            base_row = self._n_rows
            self._n_rows += len(fresh)
            self._grow(self._n_rows)
            self._child_refids[base_row : self._n_rows] = _NO_ROW  # gen reuse

            # per-node child slots FIRST: ref_node is non-decreasing (scan
            # order), so the slot index is the offset from the node's first
            # ref. Refs past the 17-slot cap (branch(16) + account storage
            # root) are dropped BEFORE interning — adversarial deep-embedded
            # RLP must not inflate the digest dict beyond the old
            # 17-per-node bound
            if len(ref_node):
                slots = np.arange(len(ref_node)) - np.searchsorted(
                    ref_node, ref_node
                )
                keep = slots < 17
                if not keep.all():
                    ref_digests = [
                        ref_digests[k] for k in np.nonzero(keep)[0].tolist()
                    ]
                    ref_node = ref_node[keep]
                    slots = slots[keep]

            # bulk refid resolution: ONE C-level map over the digest dict
            # for every digest in the batch (own digests first, then the
            # flat ref list); only genuinely new digests take the Python
            # assignment loop
            all_dig = fresh_digests + ref_digests
            ids = np.fromiter(
                map(self._refid_of_digest.get, all_dig, itertools.repeat(-1)),
                np.int64,
                len(all_dig),
            )
            missing = np.nonzero(ids < 0)[0]
            if len(missing):
                rod = self._refid_of_digest
                rid = self._n_refids
                for k in missing.tolist():
                    dg = all_dig[k]
                    got = rod.get(dg)
                    if got is None:
                        rod[dg] = got = rid
                        rid += 1
                    ids[k] = got
                self._n_refids = rid

            nfresh = len(fresh)
            self._own_refid[base_row : base_row + nfresh] = ids[:nfresh]
            if len(ref_node):
                self._child_refids[base_row + ref_node, slots] = ids[nfresh:]
            for j, nb in enumerate(fresh):
                row_of_bytes[nb] = base_row + j
            if len(fresh_idx) == len(novel):
                actual[:] = base_row + np.arange(nfresh)
            else:
                actual[np.asarray(fresh_idx, np.int64)] = base_row + np.arange(
                    nfresh
                )

        # patch forward refs through the actual-row map
        neg = rows < -1
        if neg.any():
            rows[neg] = actual[-2 - rows[neg]]

    def _intern_locked(self, nodes: Sequence[bytes]) -> np.ndarray:
        """Rows for `nodes`, hashing the never-seen ones in one batch.
        Caller holds `self._lock`."""
        rows, novel, miss = self._scan_rows_locked(nodes)
        hits_before = self.stats["hits"]
        self.stats["hits"] += len(nodes) - miss
        if novel:
            if (
                len(self._row_of_bytes) + len(novel) > self._max_nodes
                and self._row_of_bytes  # an over-cap single batch still runs
            ):
                # NOT _over_cap_locked: this path interns into the PYTHON
                # tables even on an engine whose verify path runs a C core
                # (the public intern() entry), so the flush — immediate or
                # deferred — must clear the python tables specifically;
                # routing it to the core would leave _row_of_bytes full
                # (and recurse forever) while wiping the warm core cache
                if self._inflight:
                    self._evict_pending_py = True
                else:
                    # the pass above is discarded — roll back its hit
                    # tally so the stats RPC doesn't double-count the
                    # re-interned scan
                    self.stats["hits"] = hits_before
                    if self._ext_core is None and self._core is None:
                        # the python tables ARE this engine's verify
                        # core: a real generation flush, tiered like
                        # every other scan site (pins re-commit, room
                        # reserved for this batch's novels)
                        self._evict_now_locked(incoming_novel=len(novel))
                    else:
                        self._evict_all()
                        self._flush_attached_locked()  # generation flush
                    # re-intern into the new generation (lock already held)
                    return self._intern_locked(nodes)
            self._advisory_add(novel)
            digests = self._hash_batch(novel)
            self.stats["hashed"] += len(novel)
            self.stats["novel_bytes"] = self.stats.get("novel_bytes", 0) + sum(
                map(len, novel)
            )
            if self._pin is not None:
                self._pin.note_novel(novel, digests)
            self._commit_novel_locked(rows, novel, digests)
        return rows

    # -- verification -------------------------------------------------------

    def verify_batch(
        self, witnesses: Sequence[Tuple[bytes, Sequence[bytes]]]
    ) -> np.ndarray:
        """(n_blocks,) bool — full linked-multiproof verdict per block.

        Block b verifies iff some node's digest equals root_b AND every node
        is that root or is hash-referenced by another node of block b
        (exactly witness_verify_fused's semantics; references are acyclic
        because a cycle would be a keccak collision).

        Instrumented at BATCH granularity (per-node bookkeeping would be
        measurable overhead on the hot path): cache hit/miss/eviction and
        novel-bytes counters from the stats delta, interned-set gauges, and
        the hash / intern / linkage-join phase split in the registry. The
        delta is captured under the engine lock so concurrent callers can
        never double-count each other's work; the registry publish happens
        after release (the metrics lock never nests inside ours)."""
        if self._resident_wanted():
            # the resident route is inherently two-phase (the verdict is
            # an async device program); the one-call API is begin+resolve
            # fused — verdict semantics stay byte-identical (the host
            # scan is authoritative, differential-tested)
            return self.resolve_batch(self.begin_batch(witnesses))
        if self._depth is not None:
            self._depth.record(witnesses)
        with metrics.phase("witness_engine.verify_batch"):
            with self._lock:
                # eviction-window wait FIRST (it releases the lock, see
                # _pack_handle): only then is the s0 snapshot race-free
                # against a concurrent resolver's already-published stats
                self._await_evict_window_locked()
                if not self._inflight:
                    self._run_deferred_evictions_locked()
                s0 = dict(self.stats)
                verdict = self._verify_batch_locked(witnesses)
                s1 = self.stats
                deltas = [
                    (metric, s1.get(stat_key, 0) - s0.get(stat_key, 0))
                    for stat_key, metric in (
                        ("hits", "witness_engine.cache_hits"),
                        ("hashed", "witness_engine.cache_misses"),
                        ("novel_bytes", "witness_engine.novel_bytes_hashed"),
                    )
                ]
                evict_tiers = self._evictions_by_tier(s0, s1)
                snap = self._stats_snapshot_locked()
        for metric, d in deltas:
            if d:
                # names come from the literal tuple above — all three are
                # in METRIC_HELP; the loop only exists to batch the
                # registry calls outside the engine lock
                metrics.count(metric, d)  # phantlint: disable=METRICNAME — names from the literal tuple above
        for tier, d in evict_tiers:
            metrics.count("witness_engine.evictions", d, tier=tier)
        metrics.gauge_set("witness_engine.interned_nodes", snap["interned_nodes"])
        metrics.gauge_set(
            "witness_engine.interned_digests", snap["interned_digests"]
        )
        return verdict

    # -- pipelined two-phase API (pack / dispatch / resolve) -----------------

    def _advisory_add(self, nodes) -> None:
        """Commit-site hook for the prefetch advisory set: a no-op until
        the first prefetch_batch activates it (no consumer, no copy)."""
        if self._advisory_active:
            self._seen_advisory.update(nodes)

    def _advisory_activate(self) -> None:
        """First prefetch_batch call: start maintaining the advisory set.
        The python core's committed bytes are its _row_of_bytes keys —
        seed exactly (key references, no byte copies). The C cores hold
        bytes natively; they warm with commits from here on."""
        with self._lock:
            if not self._advisory_active:
                if self._ext_core is None and self._core is None:
                    self._seen_advisory.update(self._row_of_bytes)
                self._advisory_active = True

    def prefetch_batch(
        self, witnesses: Sequence[Tuple[bytes, Sequence[bytes]]]
    ) -> PrefetchPlan:
        """STAGE 0 of the 4-stage serving pipeline (PR 9): witness
        decode + advisory novelty pre-scan for a batch that will be
        `begin_batch`'d next — host batch assembly, the candidate-novel
        scan against the advisory committed-bytes set, the candidates'
        child-reference RLP decode (python core), and pre-filled staging
        leases (native pack blob / device dispatch blob). A prefetch
        worker runs this while the previous batch is in dispatch/resolve,
        so the pack stage's critical-path work shrinks to the lock-held
        re-check + commit.

        Read-only against the tables: the advisory set is probed WITHOUT
        the engine lock (GIL-atomic membership reads racing concurrent
        commits benignly). The staleness contract is absolute — the
        pack-time scan under the lock stays the authoritative commit, so
        a stale plan (concurrent commit, generation flush, shed jobs) is
        dropped at a perf cost of zero correctness risk. Pass the SAME
        witnesses list to `begin_batch(witnesses, prefetch=plan)`; an
        unused plan must be `release()`d."""
        with metrics.phase("witness_engine.prefetch"):
            return self._prefetch_plan(witnesses)

    def _prefetch_plan(self, witnesses) -> PrefetchPlan:
        # phantlint: disable=LOCK — double-checked activation: this
        # GIL-atomic read only short-circuits the common case; a stale
        # False costs one _advisory_activate call, which re-checks the
        # flag UNDER the lock before doing anything
        if not self._advisory_active:
            self._advisory_activate()
        plan = PrefetchPlan()
        plan.witnesses = witnesses
        n_blocks = len(witnesses)
        all_nodes: List[bytes] = []
        counts = np.empty(n_blocks, np.int64)
        for b, (_root, nodes) in enumerate(witnesses):
            counts[b] = len(nodes)
            all_nodes.extend(nodes)
        plan.all_nodes = all_nodes
        plan.counts = counts
        # phantlint: disable=LOCK — advisory pre-scan, deliberately
        # lock-free: set membership under the GIL is atomic, a racing
        # commit only makes the answer stale, and stale is re-checked by
        # the authoritative pack-time scan (the staleness contract)
        seen = self._seen_advisory
        novel: List[bytes] = []
        dedup = set()
        for nb in all_nodes:
            if nb not in seen and nb not in dedup:
                dedup.add(nb)
                novel.append(nb)
        plan.novel = novel
        with self._lock:
            ext, core = self._ext_core, self._core
        if ext is None and core is not None:
            # the native core's scan/commit consume the packed C-ABI
            # blob: lease + fill it here, off the serving critical path
            plan.pack_lease = self._pack_entry(len(all_nodes))
            plan.packed = self._pack_blob(all_nodes, plan.pack_lease[1])
        if ext is None and core is None and novel:
            # python core: the commit's child-ref extraction is host-side
            # RLP parsing — decode the candidates here. Content-derived,
            # so a hint can never go stale (only unused).
            refs, ref_node = self._refs_for_batch(novel)
            by_node: Dict[bytes, list] = {nb: [] for nb in novel}
            for r, i in zip(refs, ref_node.tolist()):
                by_node[novel[i]].append(r)
            plan.refs = by_node
        if (
            novel
            and self._hasher is None
            and not self._resident_wanted()
            and not self._native_route_certain()
            and self._device_route_wanted(novel)
        ):
            try:
                plan.device_lease = self._stage_device_blob(novel)
            except ValueError:
                pass  # oversized node: dispatch will route native anyway
        return plan

    def begin_batch(
        self,
        witnesses: Sequence[Tuple[bytes, Sequence[bytes]]],
        prefetch: Optional[PrefetchPlan] = None,
    ) -> BatchHandle:
        """Pack + dispatch one verify batch WITHOUT the device round-trip:
        the engine lock is held only for the intern-table scan (pack), the
        device keccak of the novel nodes is enqueued with no host sync
        (dispatch), and everything that needs the digests — readback,
        commit, linkage join — waits for `resolve_batch`. Batch N+1 can
        therefore pack while batch N computes and batch N-1 resolves (the
        serving scheduler's pipeline, phant_tpu/serving/scheduler.py).

        Handles may be resolved in ANY order (tables are append-only and
        commits re-check membership, so interleavings — including several
        schedulers sharing one engine — stay sound; the serving resolve
        worker happens to be FIFO for per-requester ordering);
        `verify_batch` remains the one-call depth-1 equivalent and may
        interleave freely with in-flight handles.

        `prefetch` consumes a plan from `prefetch_batch` run over the
        SAME witnesses list: pack reuses the plan's assembly + staging
        leases, and when the authoritative scan confirms the plan's
        candidate-novel set the device dispatch reuses its pre-filled
        blob too. A mismatched/stale plan is released and ignored —
        the plan is advisory, this scan is the commit."""
        if self._depth is not None:
            self._depth.record(witnesses)
        plan = prefetch
        if plan is not None and plan.witnesses is not witnesses:
            # not the batch this plan was computed for: drop it whole
            plan.release()
            plan = None
        with metrics.phase("witness_engine.pack"):
            h = self._pack_handle(witnesses, plan)
        used = plan is not None and h.novel == plan.novel
        if plan is not None:
            if used:
                metrics.count("witness_engine.prefetch_plan_hits")
            else:
                metrics.count("witness_engine.prefetch_plan_stale")
            if plan.refs is not None and h.kind == "python":
                # content-derived: valid even under a stale candidate
                # set (the commit only uses it when it covers every
                # fresh node)
                h.ref_hint = plan.refs
        with metrics.phase("witness_engine.dispatch"):
            if self._resident_wanted():
                # device-resident route: update (novel bytes only) +
                # verdict enqueued with no host sync; the host tables
                # will commit from the device digests at resolve
                h.resident = self._resident_dispatch(witnesses, h.novel)
            if h.resident is None and h.novel and self._hasher is None and (
                not self._native_route_certain()
                and self._device_route_wanted(h.novel)
            ):
                staged = None
                if used and plan.device_lease is not None:
                    # ownership moves to the dispatch (lease returns to
                    # the pool at resolve, or on enqueue failure)
                    staged, plan.device_lease = plan.device_lease, None
                try:
                    h.device = self._device_dispatch(
                        h.novel, self._pinned_device(), staged=staged
                    )
                except Exception:
                    import logging

                    logging.getLogger("phant.witness").warning(
                        "device keccak dispatch failed for %d nodes; "
                        "native fallback at resolve",
                        len(h.novel),
                        exc_info=True,
                    )
        if plan is not None:
            plan.release()  # whatever was not consumed goes back pooled
        return h

    def _pack_handle(
        self, witnesses, plan: Optional[PrefetchPlan] = None
    ) -> BatchHandle:
        h = BatchHandle()
        h.n_blocks = len(witnesses)
        with self._lock:
            # core refs are write-once in __init__; alias them under the
            # lock once so the pre-lock assembly below branches on a
            # consistent snapshot (LOCK discipline)
            ext, core = self._ext_core, self._core
        all_nodes: List[bytes] = []
        if ext is None:
            # host-side batch assembly + blob packing stays OUTSIDE the
            # lock: it touches no engine table, and it is exactly the work
            # the pipeline overlaps with the previous batch's resolve —
            # or, with a prefetch plan, the work ALREADY DONE off the
            # critical path (assembly is content-derived from the same
            # witnesses list, so it is valid even when the plan's novelty
            # pre-scan went stale)
            if plan is not None and plan.all_nodes is not None:
                all_nodes = plan.all_nodes
                h.counts = plan.counts
                if core is not None and plan.packed is not None:
                    # staging ownership moves plan -> handle (the lease
                    # returns to the pool at resolve, like every pack)
                    h.pack_entry = plan.pack_lease
                    h.joined, h.blob, h.offsets, h.lens = plan.packed
                    plan.pack_lease = plan.packed = None
            else:
                counts = np.empty(h.n_blocks, np.int64)
                for b, (_root, nodes) in enumerate(witnesses):
                    counts[b] = len(nodes)
                    all_nodes.extend(nodes)
                h.counts = counts
            if core is not None and h.pack_entry is None:
                h.pack_entry = self._pack_entry(len(all_nodes))
                h.joined, h.blob, h.offsets, h.lens = self._pack_blob(
                    all_nodes, h.pack_entry[1]
                )
        with self._lock:
            # the eviction-window wait RELEASES the lock: the stats
            # snapshot for this batch's delta must come after it, or a
            # concurrent resolver's flush (already published by its own
            # resolve_batch) would be counted into the registry twice
            self._await_evict_window_locked()
            if not self._inflight:
                self._run_deferred_evictions_locked()
            s0 = dict(self.stats)
            if ext is not None:
                h.kind = "ext"
                h.ext_batch, novel, miss, total = ext.scan_begin(witnesses)
                if self._over_cap_locked(len(novel), ext.nodes()):
                    h.ext_batch, novel, miss, total = ext.scan_begin(witnesses)
                h.novel, h.miss, h.total = novel, miss, total
            elif self._core is not None:
                h.kind = "native"
                core = self._core
                rows, novel_idx, miss = core.scan(h.blob, h.offsets, h.lens)
                if self._over_cap_locked(len(novel_idx), core.nodes):
                    rows, novel_idx, miss = core.scan(h.blob, h.offsets, h.lens)
                h.rows, h.novel_idx, h.miss = rows, novel_idx, miss
                h.total = len(all_nodes)
                h.novel = [all_nodes[i] for i in novel_idx.tolist()]
                h.roots = b"".join(root for root, _nodes in witnesses)
            else:
                h.kind = "python"
                rows, novel, miss = self._scan_rows_locked(all_nodes)
                if self._over_cap_locked(len(novel), len(self._row_of_bytes)):
                    rows, novel, miss = self._scan_rows_locked(all_nodes)
                h.rows, h.novel, h.miss = rows, novel, miss
                h.total = len(all_nodes)
                h.witnesses = witnesses
            self.stats["hits"] += h.total - h.miss
            h.n_novel = len(h.novel)
            if self._pin is not None:
                # roots are depth-0 digests by definition (tier tracker)
                self._pin.note_roots([root for root, _nodes in witnesses])
            if h.novel:
                # optimistic advisory update at SCAN time: the commit is
                # coming; an abandoned handle over-approximates, which
                # only costs the prefetch pre-scan accuracy
                self._advisory_add(h.novel)
                self.stats["hashed"] += len(h.novel)
                self.stats["novel_bytes"] = self.stats.get(
                    "novel_bytes", 0
                ) + sum(map(len, h.novel))
            self._inflight += 1
            evict_tiers = self._evictions_by_tier(s0, self.stats)
        # registry publishes after release (the metrics lock never nests
        # inside ours — same discipline as verify_batch)
        for tier, d in evict_tiers:
            metrics.count("witness_engine.evictions", d, tier=tier)
        return h

    def resolve_batch(self, handle: BatchHandle) -> np.ndarray:
        """(n_blocks,) bool verdicts for a handle from `begin_batch`:
        digest readback (device) or novel-node hashing (host — on THIS
        thread, outside the engine lock, so a resolve worker's C keccak
        overlaps the executor's next pack), then commit + linkage join
        under the lock. Verdict semantics are byte-identical to
        `verify_batch` over the same witnesses."""
        with metrics.phase("witness_engine.resolve"):
            verdict, snap = self._resolve_handle(handle)
        res = handle.resident
        if res is not None:
            if res.uploaded_nodes:
                metrics.count(
                    "witness_resident.uploaded_nodes", res.uploaded_nodes
                )
                metrics.count(
                    "witness_resident.uploaded_bytes", res.uploaded_bytes
                )
            if res._table is not None:
                metrics.gauge_set("witness_resident.rows", res._table.rows())
        if handle.total:
            hits = handle.total - handle.miss
            if hits:
                metrics.count("witness_engine.cache_hits", hits)
        metrics.gauge_set("witness_engine.interned_nodes", snap["interned_nodes"])
        metrics.gauge_set(
            "witness_engine.interned_digests", snap["interned_digests"]
        )
        return verdict

    def abandon_batch(self, handle: BatchHandle) -> None:
        """Release a handle WITHOUT committing it — the crash path.
        Dropping a scanned batch is sound (commit is all-or-nothing under
        the lock, so no table state is half-applied); what MUST not leak
        is the pipeline bookkeeping: a stranded in-flight count would
        defer generation flushes forever on a shared engine that outlives
        a dead scheduler, growing the intern tables without bound.
        Idempotent; called by resolve_batch's own pre-commit failure path
        and by the serving scheduler's _die for dispatched-but-unresolved
        handles."""
        if handle.resolved:
            return
        handle.resolved = True
        with self._lock:
            self._release_inflight_locked()
        if handle.pack_entry is not None:
            # the commit that would have consumed the staging views is
            # never coming: the lease goes straight back to the pool.
            # (A device lease stays stranded — the enqueued compute may
            # still be reading its buffers; bounded loss on a crash path.)
            key, entry = handle.pack_entry
            handle.blob = handle.offsets = handle.lens = handle.joined = None
            _staging.give(key, entry)
            handle.pack_entry = None
        if handle.resident is not None:
            # the resident UPDATE was already enqueued and its row
            # assignments stand — that is consistent: the device rows
            # exist, the host prune knows it, and the host core (never
            # committed) simply re-reports those nodes as novel next
            # time, where the prune skips the re-upload. The verdict/
            # digest outputs are dropped unread; the index drop-count
            # scalars go BACK to the table (the stat must not undercount
            # across a crash path).
            handle.resident.verdict_out = None
            handle.resident.digest_out = None
            if handle.resident.dropped_outs and handle.resident._table is not None:
                handle.resident._table.return_dropped(
                    handle.resident.dropped_outs
                )
            handle.resident.dropped_outs = []
        handle.novel = []
        handle.witnesses = None
        handle.ext_batch = None

    def _resolve_handle(self, h: BatchHandle):
        if h.resolved:
            raise RuntimeError("batch handle already resolved")
        digests: Optional[List[bytes]] = None
        backend = None
        n_novel = len(h.novel)
        with self._lock:
            # write-once core ref, aliased under the lock (LOCK
            # discipline); the hashing below deliberately runs OUTSIDE it
            ext = self._ext_core
        # host-routed ext batches hash IN C into batch-local digest
        # storage — same zero-Python-round-trip keccak as _verify_ext's
        # finish_native, but split out so it runs WITHOUT the engine lock
        # (GIL released too): the executor's next pack scans the tables
        # concurrently. Any override or open offload gate surfaces the
        # novel list to the Python-visible route instead.
        ext_native_fast = (
            h.resident is None
            and h.kind == "ext"
            and n_novel > 0
            # tiered eviction needs the novel digests at the Python level
            # (the pin tracker classifies on them); route through the
            # batch keccak + finish instead of the in-C finish_native —
            # same C hashing, one extra round trip, and novel counts go
            # to ~0 in the steady state anyway
            # phantlint: disable=LOCK — `_pin` is assigned once in __init__ and never rebound; the tracker's own state only mutates under the engine lock
            and self._pin is None
            and self._native_route_certain()
        )
        verdict_dev = None
        try:
            if h.resident is not None:
                # resident route: the device computed BOTH the verdict
                # and the novel digests the host tables commit from —
                # the host hashes nothing, the readback is 1 B/block +
                # 32 B/core-novel (witness_resident.ResidentBatch)
                verdict_dev, res_digests = h.resident.resolve()
                digests = res_digests or None
                backend = "resident"
            elif h.device is not None:
                digests = h.device.resolve()  # the honest sync (keccak_jax)
                backend = "device"
            elif ext_native_fast:
                backend = "native"
                with metrics.phase("witness_engine.hash"):
                    ext.hash_batch(h.ext_batch)
            elif h.novel:
                with metrics.phase("witness_engine.hash"):
                    digests, backend = self._hash_novel(
                        h.novel, route_device=False
                    )
        except BaseException:
            # readback/hash died BEFORE any commit: release the handle so
            # the pipeline bookkeeping (and deferred evictions) survive
            self.abandon_batch(h)
            raise
        with self._lock:
            s0 = dict(self.stats)
            try:
                if h.kind == "ext":
                    with metrics.phase("witness_engine.linkage_join"):
                        # digests=None: no novels, or hash_batch already
                        # filled the batch-local digests (C side commits
                        # straight from them)
                        raw = self._ext_core.finish_batch(
                            h.ext_batch,
                            b"".join(digests) if digests else None,
                        )
                    verdict = np.frombuffer(raw, np.uint8).astype(bool)
                elif h.kind == "native":
                    if n_novel:
                        self._core.commit(
                            h.blob, h.offsets, h.lens, h.rows, h.novel_idx,
                            b"".join(digests),
                        )
                    if verdict_dev is None:
                        block_offs = np.zeros(h.n_blocks + 1, np.uint64)
                        np.cumsum(h.counts, dtype=np.uint64, out=block_offs[1:])
                        with metrics.phase("witness_engine.linkage_join"):
                            verdict = self._core.verdict(
                                h.rows, block_offs, h.roots
                            )
                else:
                    if n_novel:
                        self._commit_novel_locked(
                            h.rows, h.novel, digests, ref_hint=h.ref_hint
                        )
                    if verdict_dev is None:
                        with metrics.phase("witness_engine.linkage_join"):
                            verdict = self._linkage_join(
                                h.witnesses, h.rows, h.counts, h.n_blocks
                            )
                if self._pin is not None and digests and n_novel:
                    # novel digests surfaced (device / native / resident
                    # readback): classify them for the tiered flush
                    self._pin.note_novel(h.novel, digests)
                if verdict_dev is not None:
                    # the device join IS the verdict on the resident
                    # route (the host join is skipped — the ext core's
                    # fused commit+join is the one place it still runs,
                    # and the two are differential-tested identical)
                    verdict = verdict_dev
                if backend in ("device", "native"):
                    key = backend + "_batches"
                    self.stats[key] = self.stats.get(key, 0) + 1
                elif backend == "resident":
                    self.stats["resident_batches"] = (
                        self.stats.get("resident_batches", 0) + 1
                    )
                    # a resident batch IS a device batch for routing/
                    # record classification (batch_record_from_stats)
                    self.stats["device_batches"] = (
                        self.stats.get("device_batches", 0) + 1
                    )
            finally:
                # a failed commit poisons THIS batch but must not wedge the
                # pipeline bookkeeping (deferred evictions would never run)
                h.resolved = True
                self._release_inflight_locked()
            evict_tiers = self._evictions_by_tier(s0, self.stats)
            snap = self._stats_snapshot_locked()
        for tier, d in evict_tiers:
            # a resolve-drain flush counts like any other (pack publishes
            # its delta the same way — the metric must not undercount)
            metrics.count("witness_engine.evictions", d, tier=tier)
        if n_novel:
            metrics.count("witness_engine.cache_misses", n_novel)
            metrics.count(
                "witness_engine.novel_bytes_hashed", sum(map(len, h.novel))
            )
        if h.pack_entry is not None:
            # the staging offsets buffer is dead only now (commit/verdict
            # consumed the views) — back to the pool for the next batch
            key, entry = h.pack_entry
            h.blob = h.offsets = h.lens = h.joined = None
            _staging.give(key, entry)
            h.pack_entry = None
        h.resolved = True
        h.novel = []
        h.witnesses = None
        h.ext_batch = None
        return verdict, snap

    @staticmethod
    def _evictions_by_tier(s0: dict, s1: dict) -> List[Tuple[str, int]]:
        """(tier, delta) pairs for the `witness_engine.evictions{tier=}`
        metric from a stats delta captured under the engine lock:
        tier="deep" pinned the shallow set and evicted only the deeper
        tiers, tier="full" dropped everything (tiering off, or no pins),
        tier="twin" flushed only the python twin tables of a C-core
        engine (the public intern() overflow path). Publishing happens
        at the caller, outside the lock."""
        out: List[Tuple[str, int]] = []
        tiered = 0
        for tier in ("deep", "full"):
            d = s1.get("evictions_" + tier, 0) - s0.get("evictions_" + tier, 0)
            tiered += d
            if d:
                out.append((tier, d))
        twin = s1.get("evictions", 0) - s0.get("evictions", 0) - tiered
        if twin:
            out.append(("twin", twin))
        return out

    def _release_inflight_locked(self) -> None:
        """Drop one in-flight handle (resolve or abandon). When the
        pipeline empties, run any deferred eviction RIGHT HERE — under
        sustained pipelined load the executor's next begin overlaps this
        resolve, so 'check at the next begin' alone can starve the flush
        indefinitely and grow the tables without bound — and wake begins
        waiting for a flush window."""
        self._inflight -= 1
        if self._inflight == 0:
            self._run_deferred_evictions_locked()
            self._drained.notify_all()

    def _run_deferred_evictions_locked(self) -> None:
        """Any deferred generation flushes, each against ITS tables.
        Caller holds the lock with an empty pipeline."""
        if self._evict_pending:
            self._evict_pending = False
            self._evict_now_locked()
        if self._evict_pending_py:
            # intern() on a C-core engine overfilled the python twin:
            # flush those dicts only, never the warm core cache
            self._evict_pending_py = False
            self._evict_all()

    def _interned_nodes_locked(self) -> int:
        if self._ext_core is not None:
            return self._ext_core.nodes()
        if self._core is not None:
            return self._core.nodes
        return len(self._row_of_bytes)

    def _await_evict_window_locked(self) -> None:
        """Hard ceiling on deferred-eviction overshoot: when the tables
        have grown past 2x max_nodes with a flush still pending, make the
        over-cap begin WAIT (bounded) for the pipeline to drain instead
        of deferring again — a saturated pipeline never has a natural
        idle point, and unbounded deferral would unbound memory. The
        timeout keeps a caller that begins without a concurrent resolver
        (API misuse) degraded-but-alive rather than deadlocked."""
        over_core = (
            self._evict_pending
            and self._interned_nodes_locked() > 2 * self._max_nodes
        )
        over_py = (
            self._evict_pending_py
            and len(self._row_of_bytes) > 2 * self._max_nodes
        )
        if not (self._inflight and (over_core or over_py)):
            return
        import time

        deadline = time.monotonic() + 2.0
        while self._inflight and time.monotonic() < deadline:
            self._drained.wait(0.05)
        # _release_inflight_locked already flushed if the pipe drained

    def _over_cap_locked(self, n_novel: int, n_existing: int) -> bool:
        """THE eviction policy, shared by every scan site (classic verify
        paths and the pipelined pack stage): when this batch's novels
        would cross `max_nodes` over a non-empty table, either flush now
        (pipeline empty — returns True, caller MUST rescan against the
        fresh generation) or defer (`_evict_pending`, handles in flight —
        a flush would strand their scanned row ids; the flush then runs
        at the next pipeline drain, see _release_inflight_locked)."""
        if not (
            n_novel
            and n_existing  # an over-cap single batch still runs
            and n_existing + n_novel > self._max_nodes
        ):
            return False
        if self._inflight:
            self._evict_pending = True
            return False
        self._evict_now_locked(incoming_novel=n_novel)
        return True

    def _evict_now_locked(self, incoming_novel: int = 0) -> None:
        """Generation flush on whichever core is live. Caller holds the
        lock AND has checked `self._inflight == 0` — flushing under an
        outstanding pipelined batch would strand its scanned row ids.

        With tiered eviction (`_pin`), the flush is DEPTH-TIERED: the
        shallow pinned set (depth <= pin_depth, shallow-first within the
        budget) re-commits into the fresh generation with its remembered
        digests — no re-hashing — while everything deeper evicts
        generationally. `incoming_novel` reserves room for the batch
        that triggered the flush, so pins can never crowd out live
        traffic (and a single over-cap batch degrades to the flat
        flush). The tier label rides the evictions metric: tier="deep"
        evicted only the deep tiers, tier="full" dropped everything."""
        pinned: List[tuple] = []
        if self._pin is not None:
            room = self._max_nodes - incoming_novel
            if room > 0:
                pinned = self._pin.pinned_snapshot()[:room]
        self.stats["evictions"] += 1
        tier = "deep" if pinned else "full"
        self.stats["evictions_" + tier] = self.stats.get(
            "evictions_" + tier, 0
        ) + 1
        if self._ext_core is not None:
            self._ext_core.flush()
        elif self._core is not None:
            self._core.flush()
        else:
            self._row_of_bytes.clear()
            self._refid_of_digest.clear()
            self._n_rows = 0
            self._n_refids = 0
        self._seen_advisory.clear()
        if pinned:
            self._recommit_pinned_locked(pinned)
        self.stats["pinned_retained"] = len(pinned)
        self._flush_attached_locked(pinned)

    def _recommit_pinned_locked(self, pinned: Sequence[tuple]) -> None:
        """Insert the pinned shallow set into the just-flushed generation
        with its REMEMBERED digests — the scan/commit protocols every
        core already exposes, fed known digests instead of fresh keccak.
        The ext core runs one throwaway scan_begin/finish_batch pair
        (the verdict of the dummy block is discarded); row/refid spaces
        restart at zero with the pins as the first rows on every core,
        so cross-core parity holds."""
        nodes = [nb for nb, _dg, _d in pinned]
        dmap = {nb: dg for nb, dg, _d in pinned}
        if self._ext_core is not None:
            batch, novel, _miss, _total = self._ext_core.scan_begin(
                [(b"\x00" * 32, nodes)]
            )
            self._ext_core.finish_batch(
                batch, b"".join(dmap[nb] for nb in novel) if novel else None
            )
        elif self._core is not None:
            joined, blob, offsets, lens = self._pack_blob(nodes)
            rows, novel_idx, _miss = self._core.scan(blob, offsets, lens)
            if len(novel_idx):
                self._core.commit(
                    blob,
                    offsets,
                    lens,
                    rows,
                    novel_idx,
                    b"".join(dmap[nodes[i]] for i in novel_idx.tolist()),
                )
            del joined  # kept alive across the ctypes calls above
        else:
            rows, novel, _miss = self._scan_rows_locked(nodes)
            if novel:
                self._commit_novel_locked(
                    rows, novel, [dmap[nb] for nb in novel]
                )
        self._advisory_add(nodes)

    def _verify_batch_locked(
        self, witnesses: Sequence[Tuple[bytes, Sequence[bytes]]]
    ) -> np.ndarray:
        # deferred evictions already ran in verify_batch, BEFORE its
        # stats snapshot (the eviction-window wait releases the lock)
        if self._ext_core is not None:
            return self._verify_ext(witnesses)
        n_blocks = len(witnesses)
        all_nodes: List[bytes] = []
        counts = np.empty(n_blocks, np.int64)
        for b, (_root, nodes) in enumerate(witnesses):
            counts[b] = len(nodes)
            all_nodes.extend(nodes)
        if self._core is not None:
            return self._verify_native(witnesses, all_nodes, counts, n_blocks)
        return self._verify_interned(witnesses, all_nodes, counts, n_blocks)

    def _verify_ext(self, witnesses):
        """Two-call scan/finish protocol against the CPython extension
        driver — no batch assembly on the Python side at all. When the
        hashing route is provably the host (no hasher override, no device
        floor, and the offload gate cannot fire), the novel nodes hash
        inside the extension (finish_native) with zero Python round trip;
        otherwise the novel list comes back here so the backend route
        applies identically to every core."""
        st = self._ext_core
        if self._pin is not None:
            self._pin.note_roots([root for root, _nodes in witnesses])
        with metrics.phase("witness_engine.intern"):
            novel, miss, total = st.scan(witnesses)
        n_novel = len(novel)
        if n_novel:
            if self._over_cap_locked(n_novel, st.nodes()):
                with metrics.phase("witness_engine.intern"):
                    novel, miss, total = st.scan(witnesses)
                n_novel = len(novel)
            self._advisory_add(novel)
            route_device = not self._native_route_certain() and (
                self._device_route_wanted(novel)
            )
            self.stats["novel_bytes"] = self.stats.get("novel_bytes", 0) + sum(
                map(len, novel)
            )
            if not route_device and self._pin is None:
                # the routed hasher for THIS batch is the host: hash inside
                # the extension, zero Python round trip.  (With the Pallas
                # kernel the offload gate is open in principle, so the
                # structural short-circuit alone no longer covers the
                # common native case — the per-batch cost-model verdict
                # does, at the price of one cached link-profile read.)
                self.stats["hashed"] += n_novel
                self.stats["native_batches"] = (
                    self.stats.get("native_batches", 0) + 1
                )
                # finish_native hashes + commits + joins in one C call; it
                # times as "hash" because the novel-node keccak dominates
                with metrics.phase("witness_engine.hash"):
                    verdict = st.finish_native()
            else:
                # device-routed, or tiered eviction needs the digests at
                # the Python level: the batch keccak route (device or
                # native per the cost model) surfaces them
                digests = self._hash_batch(novel, route_device=route_device)
                self.stats["hashed"] += n_novel
                if self._pin is not None:
                    self._pin.note_novel(novel, digests)
                with metrics.phase("witness_engine.linkage_join"):
                    verdict = st.finish(b"".join(digests))
        else:
            with metrics.phase("witness_engine.linkage_join"):
                verdict = st.finish(None)
        self.stats["hits"] += total - miss
        return np.frombuffer(verdict, np.uint8).astype(bool)

    def _device_route_wanted(self, nodes: List[bytes]) -> bool:
        """THE routing predicate: would this batch go to the device?
        Shared by _hash_batch (which acts on it) and _verify_ext (which
        uses it to keep the zero-round-trip finish_native fast path for
        host-routed batches), so the two can never disagree.

        A bench hasher override returns True — the batch must surface to
        the Python-visible path for the override to apply."""
        from phant_tpu.backend import (
            crypto_backend,
            device_offload_pays,
            jax_device_ok,
        )
        from phant_tpu.crypto.keccak import RATE

        if self._hasher is not None:
            return True
        # backend check FIRST: the adaptive gate probes the device link,
        # which must never happen on the pure-CPU path (a dead tunnel
        # would hang a run that never asked for a device)
        if crypto_backend() != "tpu" or not jax_device_ok():
            return False
        # nodes at/over the kernel's absorb capacity (pad byte positions
        # would fall past the gathered chunks) must take the native path —
        # witnesses are untrusted input and the digest must never be
        # silently wrong, matching pack_witness_fused's explicit raise
        if any(len(n) >= WITNESS_MAX_CHUNKS * RATE for n in nodes):
            return False
        if self._device_batch_floor >= 0:
            return len(nodes) >= self._device_batch_floor
        return device_offload_pays(sum(len(n) for n in nodes))

    def _native_route_certain(self) -> bool:
        """True when _hash_batch could only ever pick the native hasher —
        then finish_native may hash in C without consulting the route. Any
        override (bench hasher, device floor) or a cost model that could
        favor the device falls back to the Python-visible path."""
        if self._hasher is not None or self._device_batch_floor >= 0:
            return False
        from phant_tpu.backend import crypto_backend, device_offload_possible

        if crypto_backend() != "tpu":
            return True
        # tpu backend: only safe when the gate is structurally closed
        return not device_offload_possible()

    def _verify_native(self, witnesses, all_nodes, counts, n_blocks):
        """Scan/hash/commit/verdict against the C++ core. The hashing of
        novel nodes stays here so the device/native backend route (and the
        bench's hasher override) applies identically to both cores."""
        core = self._core
        n = len(all_nodes)
        if self._pin is not None:
            self._pin.note_roots([root for root, _nodes in witnesses])
        # `joined` kept alive across the ctypes calls
        joined, blob, offsets, lens = self._pack_blob(all_nodes)
        with metrics.phase("witness_engine.intern"):
            rows, novel_idx, miss = core.scan(blob, offsets, lens)
        if len(novel_idx):
            if self._over_cap_locked(len(novel_idx), core.nodes):
                with metrics.phase("witness_engine.intern"):
                    rows, novel_idx, miss = core.scan(blob, offsets, lens)
            novel = [all_nodes[i] for i in novel_idx.tolist()]
            self._advisory_add(novel)
            digests = self._hash_batch(novel)
            self.stats["hashed"] += len(novel)
            self.stats["novel_bytes"] = self.stats.get("novel_bytes", 0) + sum(
                map(len, novel)
            )
            if self._pin is not None:
                self._pin.note_novel(novel, digests)
            core.commit(blob, offsets, lens, rows, novel_idx, b"".join(digests))
        self.stats["hits"] += n - miss
        block_offs = np.zeros(n_blocks + 1, np.uint64)
        np.cumsum(counts, dtype=np.uint64, out=block_offs[1:])
        roots = b"".join(root for root, _nodes in witnesses)
        with metrics.phase("witness_engine.linkage_join"):
            return core.verdict(rows, block_offs, roots)

    def _verify_interned(self, witnesses, all_nodes, counts, n_blocks):
        # the intern phase includes the nested witness_engine.hash phase of
        # any novel nodes; linkage-join covers the integer-join verdict
        if self._pin is not None:
            self._pin.note_roots([root for root, _nodes in witnesses])
        with metrics.phase("witness_engine.intern"):
            rows = self._intern_locked(all_nodes)
        with metrics.phase("witness_engine.linkage_join"):
            return self._linkage_join(witnesses, rows, counts, n_blocks)

    def _linkage_join(self, witnesses, rows, counts, n_blocks):
        block_id = np.repeat(np.arange(n_blocks, dtype=np.int64), counts)

        # the root digest resolves through the same refid space; -1 when the
        # digest has never been seen (as a node or a reference)
        root_refid = np.fromiter(
            (self._refid_of_digest.get(root, -1) for root, _n in witnesses),
            np.int64,
            n_blocks,
        )

        # per-(block, refid) edge join, all integer ops: node ok <=> its
        # digest is the block's root, or some node of the same block has a
        # child reference to its digest. 64-bit pairing key =
        # block * stride + refid.
        own = self._own_refid[rows]  # (N,)
        children = self._child_refids[rows]  # (N, 17)
        live = children >= 0
        stride = np.int64(self._n_refids + 1)
        edge_keys = np.unique((block_id[:, None] * stride + children)[live])
        node_keys = block_id * stride + own
        if len(edge_keys):
            idx = np.searchsorted(edge_keys, node_keys)
            referenced = (idx < len(edge_keys)) & (
                edge_keys[np.minimum(idx, len(edge_keys) - 1)] == node_keys
            )
        else:
            referenced = np.zeros(len(node_keys), bool)
        is_root = own == root_refid[block_id]
        ok_node = referenced | is_root

        all_ok = np.ones(n_blocks, bool)
        np.logical_and.at(all_ok, block_id, ok_node)
        # some node of the block must actually hash to the root (a root
        # refid that exists only as a reference is not enough)
        root_present = np.zeros(n_blocks, bool)
        np.logical_or.at(root_present, block_id, is_root)
        return all_ok & root_present & (counts > 0)

    def verify(self, state_root: bytes, nodes: Sequence[bytes]) -> bool:
        """Single-witness convenience wrapper (the Engine API path)."""
        return bool(self.verify_batch([(state_root, list(nodes))])[0])

    def resident_table(self):
        """The live device-resident table, or None (not yet engaged /
        dropped). Bench + tests read its arrays and upload accounting."""
        with self._lock:
            return self._resident

    def stats_snapshot(self) -> dict:
        """Counters + derived cache-effectiveness numbers (the public
        surface behind the phant_witnessEngineStats RPC). Takes the engine
        lock: finish_native releases the GIL mid-commit, so an unlocked
        read could otherwise observe the native tables mid-mutation."""
        with self._lock:
            return self._stats_snapshot_locked()

    def _stats_snapshot_locked(self) -> dict:
        st = dict(self.stats)
        seen = st.get("hashed", 0) + st.get("hits", 0)
        st["hit_rate"] = round(st.get("hits", 0) / seen, 4) if seen else 0.0
        if self._ext_core is not None:
            st["interned_nodes"] = self._ext_core.nodes()
            st["interned_digests"] = self._ext_core.digests()
            st["core"] = "native-ext"
        elif self._core is not None:
            st["interned_nodes"] = self._core.nodes
            st["interned_digests"] = self._core.digests
            st["core"] = "native"
        else:
            st["interned_nodes"] = len(self._row_of_bytes)
            st["interned_digests"] = len(self._refid_of_digest)
            st["core"] = "python"
        if self._device_index is not None:
            # mesh pinning surface: which pool lane this engine is, and —
            # once the device route has resolved it — the actual jax
            # device the hashing lands on
            st["device_index"] = self._device_index
            if self._pinned is not None:
                st["device"] = str(self._pinned)
        if self._pin is not None:
            # depth-tiered eviction (PR 9): the live pin classification —
            # how many shallow rows the next generation flush would
            # retain, per depth (the histogram-derived tier model)
            st["tiered_evict"] = True
            st["pin_depth"] = self._pin.pin_depth
            st["pinned_rows"] = len(self._pin._pinned)
            st["pinned_per_depth"] = {
                str(d): c for d, c in sorted(self._pin.per_depth().items())
            }
        if self._resident is not None:
            # device-resident intern table: rows/generation plus the
            # upload accounting (novel bytes shipped vs pruned) — the
            # steady-state tunnel-independence claim, auditable per lane
            st["resident"] = self._resident.stats_snapshot()
        return st
