"""Fused block-witness verification on device.

The device program receives exactly the bytes a stateless client receives —
the concatenated RLP witness nodes (blob) plus tiny metadata — and does
everything else on device: unpack each node from the blob (gather),
keccak-pad it, hash it with the batched keccak kernel, and reduce a
per-block verdict (does some node hash to the block's expected root?).
Host->device traffic is therefore the witness itself, not a padded layout
(~4x smaller, and no host-side packing loop at all).

Reference scope: the keccak/MPT hot loop (src/crypto/hasher.zig:4-17,
src/mpt/mpt.zig:38-119); the batching axis and the on-device verdict are
this framework's addition per the north star (BASELINE.json).
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from phant_tpu.crypto.keccak import RATE
from phant_tpu.ops.keccak_jax import keccak256_chunked

# Bucket bound for witness nodes: RLP trie nodes are <= 576B (BASELINE.md),
# and 576 < 5 * 136. Shared by bench.py / __graft_entry__.py / tests.
WITNESS_MAX_CHUNKS = 5


@functools.partial(jax.jit, static_argnames=("max_chunks",))
def witness_digests(
    blob: jax.Array,
    offsets: jax.Array,
    lens: jax.Array,
    *,
    max_chunks: int,
) -> jax.Array:
    """Hash every node sliced out of `blob` on device.

    Args:
      blob: (L,) uint8 — concatenated node payloads, L >= max offset+len and
        padded with at least max_chunks*RATE trailing zeros (gather slack).
      offsets: (B,) int32 — start of node i in blob.
      lens: (B,) int32 — byte length of node i (0 = padding row).
      max_chunks: static bucket bound (rate chunks per node).

    Returns:
      (B, 8) uint32 digests (little-endian words).
    """
    row = max_chunks * RATE
    pos = jnp.arange(row, dtype=jnp.int32)[None, :]  # (1, row)
    idx = offsets[:, None] + pos  # (B, row)
    data = jnp.take(blob, idx, mode="clip")
    in_range = pos < lens[:, None]
    data = jnp.where(in_range, data, jnp.uint8(0))
    # keccak multi-rate padding: 0x01 after the payload, 0x80 at the end of
    # the last rate block
    nchunks = lens // RATE + 1
    pad01 = (pos == lens[:, None]).astype(jnp.uint8)
    pad80 = (pos == nchunks[:, None] * RATE - 1).astype(jnp.uint8) << 7
    data = data ^ pad01 ^ pad80
    # u8 -> little-endian u32 lanes
    b = data.reshape(data.shape[0], max_chunks, RATE // 4, 4).astype(jnp.uint32)
    words = b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16) | (b[..., 3] << 24)
    return keccak256_chunked(words, nchunks, max_chunks=max_chunks)


@functools.partial(jax.jit, static_argnames=("max_chunks", "n_blocks"))
def witness_verify(
    blob: jax.Array,
    meta: jax.Array,
    roots: jax.Array,
    *,
    max_chunks: int,
    n_blocks: int,
) -> jax.Array:
    """Per-block root-membership verdict, entirely on device.

    meta: (3, B) int32 — rows are (offsets, lens, block_id); fused into one
      array so a batch costs two host->device transfers (blob + meta), not
      four dispatches.
    roots: (n_blocks, 8) uint32 — expected state/trie root per block.

    Returns (n_blocks,) bool — block b is verified iff some node of block b
    hashes to roots[b]. (Linkage of inner nodes is checked by the host walk
    in phant_tpu/mpt/proof.py; this kernel covers the hashing-dominated
    membership check, the hot 90%.)
    """
    offsets, lens, block_id = meta[0], meta[1], meta[2]
    digests = witness_digests(blob, offsets, lens, max_chunks=max_chunks)
    return partial_verdict(digests, lens, block_id, roots, n_blocks) > 0


def partial_verdict(digests, lens, block_id, roots, n_blocks: int):
    """(n_blocks,) int32 root-membership hits for one shard of nodes.

    Shared by the single-chip path above and the dp-sharded path
    (__graft_entry__.dryrun_multichip), which pmax-combines shards' results
    over the mesh — keeping verdict semantics in exactly one place."""
    valid = lens > 0
    is_root = jnp.all(digests == roots[block_id], axis=1) & valid
    return jnp.zeros((n_blocks,), jnp.int32).at[block_id].max(is_root.astype(jnp.int32))


# ---------------------------------------------------------------------------
# host-side layout
# ---------------------------------------------------------------------------


def pack_witness_blob(
    node_lists: Sequence[Sequence[bytes]], max_chunks: int, pad_nodes_to: int | None = None
) -> Tuple[np.ndarray, np.ndarray]:
    """Concatenate per-block node lists into (blob, meta) where meta is the
    (3, B) int32 array of (offsets, lens, block_id) rows.

    The blob gets max_chunks*RATE trailing zeros of gather slack; the node
    axis is padded to `pad_nodes_to` (default: next power of two) with
    zero-length rows so repeated calls reuse a small set of compiled shapes.
    """
    parts: List[bytes] = [n for nodes in node_lists for n in nodes]
    B = len(parts)
    counts = np.fromiter((len(nodes) for nodes in node_lists), np.int64, len(node_lists))
    lens_arr = np.fromiter((len(n) for n in parts), np.int32, B)
    if int(lens_arr.sum()) >= 2**31:
        raise ValueError("witness blob exceeds int32 offset range; split the batch")
    if B and (lens_arr // RATE + 1 > max_chunks).any():
        worst = int(lens_arr.max())
        raise ValueError(f"node of {worst}B exceeds bucket bound {max_chunks}")
    target = pad_nodes_to
    if target is None:
        target = 1
        while target < max(B, 1):
            target *= 2
    if B > target:
        raise ValueError(f"{B} nodes exceed pad_nodes_to={target}")
    meta = np.zeros((3, target), np.int32)
    if B > 1:
        np.cumsum(lens_arr[:-1], out=meta[0, 1:B])
    meta[1, :B] = lens_arr
    meta[2, :B] = np.repeat(np.arange(len(node_lists), dtype=np.int32), counts)
    blob = np.frombuffer(b"".join(parts) + b"\x00" * (max_chunks * RATE), dtype=np.uint8)
    return blob, meta


def roots_to_words(roots: Sequence[bytes]) -> np.ndarray:
    """(NB, 8) u32 little-endian view of 32-byte root hashes."""
    return np.stack([np.frombuffer(r, dtype="<u4") for r in roots])
