"""Fused block-witness verification on device.

The device program receives exactly the bytes a stateless client receives —
the concatenated RLP witness nodes (blob) plus tiny metadata — and does
everything else on device: unpack each node from the blob (gather),
keccak-pad it, hash it with the batched keccak kernel, and reduce a
per-block verdict (does some node hash to the block's expected root?).
Host->device traffic is therefore the witness itself, not a padded layout
(~4x smaller, and no host-side packing loop at all).

Reference scope: the keccak/MPT hot loop (src/crypto/hasher.zig:4-17,
src/mpt/mpt.zig:38-119); the batching axis and the on-device verdict are
this framework's addition per the north star (BASELINE.json).
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from phant_tpu.crypto.keccak import RATE
from phant_tpu.ops.keccak_jax import keccak256_chunked_auto

# Bucket bound for witness nodes: RLP trie nodes are <= 576B (BASELINE.md),
# and 576 < 5 * 136. Shared by bench.py / __graft_entry__.py / tests.
WITNESS_MAX_CHUNKS = 5


def _pow2ceil(n: int) -> int:
    p = 1
    while p < max(n, 1):
        p *= 2
    return p


def _gather_node_rows(blob, offsets, lens, row: int):
    """(B, row) uint8 — each node's bytes sliced out of the blob, zeroed
    past its length."""
    pos = jnp.arange(row, dtype=jnp.int32)[None, :]  # (1, row)
    idx = offsets[:, None] + pos  # (B, row)
    data = jnp.take(blob, idx, mode="clip")
    return jnp.where(pos < lens[:, None], data, jnp.uint8(0))


def _digests_from_rows(data, lens, *, max_chunks: int):
    """Keccak-pad gathered node rows and hash them (shared by the meta and
    fused kernels so a fused program hashes the same rows it parses)."""
    row = max_chunks * RATE
    pos = jnp.arange(row, dtype=jnp.int32)[None, :]
    # keccak multi-rate padding: 0x01 after the payload, 0x80 at the end of
    # the last rate block
    nchunks = lens // RATE + 1
    pad01 = (pos == lens[:, None]).astype(jnp.uint8)
    pad80 = (pos == nchunks[:, None] * RATE - 1).astype(jnp.uint8) << 7
    padded = data ^ pad01 ^ pad80
    # u8 -> little-endian u32 lanes
    b = padded.reshape(padded.shape[0], max_chunks, RATE // 4, 4).astype(jnp.uint32)
    words = b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16) | (b[..., 3] << 24)
    return keccak256_chunked_auto(words, nchunks, max_chunks=max_chunks)


@functools.partial(jax.jit, static_argnames=("max_chunks",))
def witness_digests(
    blob: jax.Array,
    offsets: jax.Array,
    lens: jax.Array,
    *,
    max_chunks: int,
) -> jax.Array:
    """Hash every node sliced out of `blob` on device.

    Args:
      blob: (L,) uint8 — concatenated node payloads, L >= max offset+len and
        padded with at least max_chunks*RATE trailing zeros (gather slack).
      offsets: (B,) int32 — start of node i in blob.
      lens: (B,) int32 — byte length of node i (0 = padding row).
      max_chunks: static bucket bound (rate chunks per node).

    Returns:
      (B, 8) uint32 digests (little-endian words).
    """
    data = _gather_node_rows(blob, offsets, lens, max_chunks * RATE)
    return _digests_from_rows(data, lens, max_chunks=max_chunks)


# ---------------------------------------------------------------------------
# linked (full multiproof) verification
# ---------------------------------------------------------------------------


def _gather_refs(blob, ref_off):
    """(M, 8) u32 little-endian words of the 32-byte refs at `ref_off`."""
    idx = jnp.maximum(ref_off, 0)[:, None] + jnp.arange(32, dtype=jnp.int32)[None, :]
    b = jnp.take(blob, idx, mode="clip").astype(jnp.uint32).reshape(-1, 8, 4)
    return b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16) | (b[..., 3] << 24)


# sentinel block id for pad refs: matches nothing. A plain int (NOT a jnp
# array) so importing this module for its host-side helpers never triggers
# jax backend initialization (the axon-pinned-platform hazard).
_DEAD_BLOCK = 2**30


def _referenced(digests, block_id, refs, ref_block, ref_live):
    """(N,) bool: node i's digest appears among its own block's child refs.

    Exact 256-bit equality (soundness: a truncated fingerprint would let an
    adversary link a foreign node with a crafted collision), computed as a
    sort-join instead of an (N, M) compare matrix: stack refs and digests as
    rows keyed by (block, 8 digest words), `lax.sort` lexicographically, mark
    equal-key runs, and flag a digest row iff its run contains a live ref.
    O((N+M) log(N+M)) work vs O(N*M*8) for the matrix — at bench shapes the
    matrix would rival the keccak cost itself."""
    N = digests.shape[0]
    M = refs.shape[0]
    block = jnp.concatenate(
        [
            jnp.where(ref_live, ref_block, jnp.int32(_DEAD_BLOCK)),
            block_id.astype(jnp.int32),
        ]
    )
    words = [jnp.concatenate([refs[:, k], digests[:, k]]) for k in range(8)]
    is_digest = jnp.concatenate(
        [jnp.zeros((M,), jnp.uint32), jnp.ones((N,), jnp.uint32)]
    )
    src = jnp.concatenate(
        [jnp.full((M,), N, jnp.uint32), jnp.arange(N, dtype=jnp.uint32)]
    )
    sb, *sw, stag, ssrc = jax.lax.sort(
        (block, *words, is_digest, src), num_keys=9
    )
    eq_prev = sb[1:] == sb[:-1]
    for w in sw:
        eq_prev = eq_prev & (w[1:] == w[:-1])
    eq_prev = jnp.concatenate([jnp.zeros((1,), bool), eq_prev])
    run_id = jnp.cumsum((~eq_prev).astype(jnp.int32)) - 1
    live_ref_row = (stag == 0) & (sb < _DEAD_BLOCK)
    run_has_ref = (
        jnp.zeros((N + M,), jnp.int32).at[run_id].max(live_ref_row.astype(jnp.int32))
    )
    row_ref = run_has_ref[run_id] > 0
    # scatter digest rows' flags back to node order (ref rows dump to slot N)
    out = (
        jnp.zeros((N + 1,), jnp.int32)
        .at[jnp.where(stag == 1, ssrc, jnp.uint32(N))]
        .max(row_ref.astype(jnp.int32))
    )
    return out[:N] > 0


def linked_verdict(digests, lens, block_id, refs, ref_block, ref_live, roots, n_blocks: int):
    """Per-block (root_hit, all_linked) partials as int32 arrays.

    A block verifies iff some node hashes to its root AND every node is
    either that root or hash-referenced by another witness node of the same
    block. Hash references are acyclic (a cycle would be a keccak collision),
    so this is exactly 'the witness is a connected subtree rooted at the
    claimed root' — the real multiproof verdict, not just root membership.
    Shared between the single-chip kernel and the dp-sharded path (which
    combines partials with pmax/pmin over the mesh)."""
    valid = lens > 0
    is_root = jnp.all(digests == roots[block_id], axis=1) & valid
    referenced = _referenced(digests, block_id, refs, ref_block, ref_live)
    ok_node = (~valid) | is_root | referenced
    root_hit = (
        jnp.zeros((n_blocks,), jnp.int32).at[block_id].max(is_root.astype(jnp.int32))
    )
    all_ok = (
        jnp.ones((n_blocks,), jnp.int32)
        .at[jnp.where(valid, block_id, 0)]
        .min(jnp.where(valid, ok_node, True).astype(jnp.int32))
    )
    return root_hit, all_ok


@functools.partial(jax.jit, static_argnames=("max_chunks", "n_blocks"))
def witness_verify_linked(
    blob: jax.Array,
    meta: jax.Array,
    ref_meta: jax.Array,
    roots: jax.Array,
    *,
    max_chunks: int,
    n_blocks: int,
) -> jax.Array:
    """Full multiproof witness verification on device.

    meta: (3, B) int32 — (offsets, lens, block_id) per node (0-len = pad).
    ref_meta: (2, R) int32 — (blob offset, block_id) of every 32-byte child
      hash reference inside the witness nodes (host-scanned, -1 offset = pad).
    roots: (n_blocks, 8) uint32.

    Returns (n_blocks,) bool. Unlike plain root membership,
    a block passes only if its nodes form a connected subtree rooted at the
    expected root — a witness with a broken parent->child link is rejected.
    """
    offsets, lens, block_id = meta[0], meta[1], meta[2]
    digests = witness_digests(blob, offsets, lens, max_chunks=max_chunks)
    refs = _gather_refs(blob, ref_meta[0])
    root_hit, all_ok = linked_verdict(
        digests, lens, block_id, refs, ref_meta[1], ref_meta[0] >= 0, roots, n_blocks
    )
    return (root_hit > 0) & (all_ok > 0)


# ---------------------------------------------------------------------------
# fused verification with ON-DEVICE ref extraction
#
# The RLP child-hash references of a trie node are recoverable from at most
# 17 top-level item-header decodes (all vectorizable gathers):
#   - a 17-item node (branch) references its 32-byte-string children
#     (slots 0..15); embedded (<32B) children cannot themselves contain a
#     33-byte hash reference, so no recursion is ever needed;
#   - a 2-item node is an extension (item1 if a 32-byte string) or a leaf,
#     whose account-shaped value commits a storage root at a fixed offset
#     behind 4 more header decodes.
# Running this on device removes the ref_meta transfer (~8 bytes per ref,
# the second-largest h2d stream after the blob itself) AND the host-side
# native ref scan; the host ships the raw witness plus 4 bytes per node.
# Mirrors native/packer.cc phant_scan_refs / scan_refs_py bit-for-bit
# (differential-tested) except that malformed nodes mark themselves ref-less
# (failing verification) instead of raising.
# ---------------------------------------------------------------------------


def _take_at(data, idx):
    """(B,) byte of each node row at per-node position idx (clamped)."""
    j = jnp.clip(idx, 0, data.shape[1] - 1)
    return jnp.take_along_axis(data, j[:, None], axis=1)[:, 0].astype(jnp.int32)


def _decode_rlp_header(data, pos):
    """Vectorized RLP item-header decode at per-node byte position `pos`.

    Returns (payload_start, payload_len, next_pos, ok, is_list, is_ref)
    where is_ref flags exactly the 0xa0 header (32-byte string). Length-of-
    length > 2 cannot occur in <=679B nodes and flags not-ok."""
    b0 = _take_at(data, pos)
    b1 = _take_at(data, pos + 1)
    b2 = _take_at(data, pos + 2)
    single = b0 < 0x80
    short_str = (b0 >= 0x80) & (b0 <= 0xB7)
    long_str = (b0 >= 0xB8) & (b0 <= 0xBF)
    short_list = (b0 >= 0xC0) & (b0 <= 0xF7)
    long_list = b0 >= 0xF8
    lnl = jnp.where(long_str, b0 - 0xB7, jnp.where(long_list, b0 - 0xF7, 0))
    long_len = jnp.where(lnl == 1, b1, (b1 << 8) | b2)
    plen = jnp.where(
        single,
        1,
        jnp.where(
            short_str, b0 - 0x80, jnp.where(short_list, b0 - 0xC0, long_len)
        ),
    )
    ps = jnp.where(single, pos, pos + 1 + lnl)
    return ps, plen, ps + plen, lnl <= 2, short_list | long_list, b0 == 0xA0


def _extract_ref_positions(data, lens):
    """(B, 17) int32 node-relative offsets of every child hash reference
    (-1 = no ref in that slot). Slots 0..15 are branch children; slot 16 is
    the extension child or the account-leaf storage root."""
    end = lens.astype(jnp.int32)
    zero = jnp.zeros_like(end)
    ps0, _plen0, pe0, ok0, islist0, _ = _decode_rlp_header(data, zero)
    bad = ~(ok0 & islist0 & (pe0 == end) & (end > 0))

    pos = ps0
    item_ps = []
    item_pe = []
    item_ref = []
    item_valid = []
    for _k in range(17):
        ps, _plen, nxt, ok, is_list, is_ref = _decode_rlp_header(data, pos)
        valid = (pos < end) & ~bad
        overrun = valid & (~ok | (nxt > end))
        bad = bad | overrun
        valid = valid & ~overrun
        item_ps.append(jnp.where(valid, ps, 0))
        item_pe.append(jnp.where(valid, nxt, 0))
        item_ref.append(valid & is_ref & ~is_list)
        item_valid.append(valid)
        pos = jnp.where(valid, nxt, pos)
    bad = bad | (pos != end)  # 18+ items, or trailing garbage

    n_items = sum(v.astype(jnp.int32) for v in item_valid)
    is_branch = (n_items == 17) & ~bad
    is_pair = (n_items == 2) & ~bad

    # branch: slots 0..15 that are 32-byte strings
    branch_refs = [
        jnp.where(is_branch & item_ref[k], item_ps[k], -1) for k in range(16)
    ]

    # pair: hex-prefix flag byte of item 0 (empty path = malformed)
    p0 = _take_at(data, item_ps[0])
    nonempty0 = (item_pe[0] - item_ps[0]) > 0
    is_ext = is_pair & nonempty0 & ((p0 & 0x20) == 0)
    is_leaf = is_pair & nonempty0 & ((p0 & 0x20) != 0)
    ext_ref = jnp.where(is_ext & item_ref[1], item_ps[1], -1)

    # leaf: item1 must be a string whose content is a 4-string account list
    # with 32-byte items 2 and 3 (mirrors _account_storage_root_off)
    v_ps, v_pe = item_ps[1], item_pe[1]
    l_ps, _lp, l_pe, l_ok, l_islist, _ = _decode_rlp_header(data, v_ps)
    acct = is_leaf & ~item_ref[1] & l_ok & l_islist & (l_pe == v_pe)
    q_ps, _qp, q_pe, q_ok, q_islist, _ = _decode_rlp_header(data, l_ps)  # nonce
    acct = acct & q_ok & ~q_islist & (q_pe <= l_pe)
    r_ps, _rp, r_pe, r_ok, r_islist, _ = _decode_rlp_header(data, q_pe)  # balance
    acct = acct & r_ok & ~r_islist & (r_pe <= l_pe)
    acct = (
        acct
        & (_take_at(data, r_pe) == 0xA0)
        & (_take_at(data, r_pe + 33) == 0xA0)
        & (r_pe + 66 == l_pe)
    )
    leaf_ref = jnp.where(acct, r_pe + 1, -1)

    slot16 = jnp.where(is_ext, ext_ref, leaf_ref)
    return jnp.stack(branch_refs + [slot16], axis=1)


def _ref_words_from_rows(data, ref_pos):
    """(B, 17, 8) u32 LE words of the 32-byte refs at node-relative ref_pos
    (dead slots gather garbage; callers mask with ref_pos >= 0)."""
    B = data.shape[0]
    idx = jnp.clip(ref_pos, 0, data.shape[1] - 33)[:, :, None] + jnp.arange(
        32, dtype=jnp.int32
    )[None, None, :]
    b = jnp.take_along_axis(data, idx.reshape(B, -1), axis=1).reshape(
        B, 17, 8, 4
    ).astype(jnp.uint32)
    return b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16) | (b[..., 3] << 24)


def witness_node_features(blob, offsets, lens, *, max_chunks: int):
    """(digests, ref_words, ref_live) of every node sliced out of `blob` —
    the per-node features the device-resident intern table persists
    (ops/witness_resident.py): digest (B, 8), the up-to-17 child-hash
    reference words (B, 17, 8), and which ref slots are live (B, 17).
    Composes inside jit; exactly the gather/hash/ref-extraction pipeline
    of `witness_verify_fused`, factored so the resident update scatters
    the SAME features the fused kernel computes inline (the two can never
    diverge on ref semantics — malformed nodes are ref-less on both)."""
    data = _gather_node_rows(blob, offsets, lens, max_chunks * RATE)
    digests = _digests_from_rows(data, lens, max_chunks=max_chunks)
    ref_pos = _extract_ref_positions(data, lens)
    refs = _ref_words_from_rows(data, ref_pos)
    ref_live = (ref_pos >= 0) & (lens[:, None] > 0)
    return digests, refs, ref_live


@functools.partial(jax.jit, static_argnames=("max_chunks", "n_blocks"))
def witness_verify_fused(
    blob: jax.Array,
    meta16: jax.Array,
    roots: jax.Array,
    *,
    max_chunks: int,
    n_blocks: int,
) -> jax.Array:
    """Full linked multiproof verification from the raw witness alone.

    meta16: (2, B) uint16 — (len, block_id) per node, in blob order (0-len =
      pad). Offsets are an on-device exclusive cumsum: the blob IS the
      concatenation of the nodes. Child references are parsed out of the
      node bytes on device (_extract_ref_positions) — host->device traffic
      is the witness bytes + 4 bytes per node, nothing else.

    Semantics identical to witness_verify_linked: a block verifies iff its
    nodes form a connected subtree rooted at its expected root.
    """
    lens = meta16[0].astype(jnp.int32)
    block_id = meta16[1].astype(jnp.int32)
    offsets = jnp.cumsum(lens) - lens  # exclusive
    data = _gather_node_rows(blob, offsets, lens, max_chunks * RATE)
    digests = _digests_from_rows(data, lens, max_chunks=max_chunks)
    ref_pos = _extract_ref_positions(data, lens)
    refs = _ref_words_from_rows(data, ref_pos).reshape(-1, 8)
    ref_live = (ref_pos >= 0).reshape(-1)
    ref_block = jnp.broadcast_to(block_id[:, None], ref_pos.shape).reshape(-1)
    root_hit, all_ok = linked_verdict(
        digests, lens, block_id, refs, ref_block, ref_live, roots, n_blocks
    )
    return (root_hit > 0) & (all_ok > 0)


def pack_witness_fused(
    node_lists: Sequence[Sequence[bytes]],
    max_chunks: int,
    pad_nodes_to: int | None = None,
    min_pad: int = 1,
) -> Tuple[np.ndarray, np.ndarray]:
    """(blob, meta16) for `witness_verify_fused`: the concatenated witness
    bytes plus (2, B) uint16 (len, block_id) rows — no offsets, no host ref
    scan. The cheapest possible host-side layout (~4 bytes/node of metadata
    vs 12 + 8/ref for the explicit-refs path)."""
    parts: List[bytes] = [n for nodes in node_lists for n in nodes]
    B = len(parts)
    counts = np.fromiter(
        (len(nodes) for nodes in node_lists), np.int64, len(node_lists)
    )
    lens_arr = np.fromiter((len(n) for n in parts), np.int64, B)
    if len(node_lists) > 0xFFFF:
        raise ValueError("block_id exceeds uint16; split the batch")
    if B and (lens_arr // RATE + 1 > max_chunks).any():
        raise ValueError(
            f"node of {int(lens_arr.max())}B exceeds bucket bound {max_chunks}"
        )
    if int(lens_arr.sum()) >= 2**31:
        raise ValueError("witness blob exceeds int32 offset range; split the batch")
    target = pad_nodes_to
    if target is None:
        target = _pow2ceil(max(B, min_pad))
    if B > target:
        raise ValueError(f"{B} nodes exceed pad_nodes_to={target}")
    meta16 = np.zeros((2, target), np.uint16)
    meta16[0, :B] = lens_arr
    meta16[1, :B] = np.repeat(
        np.arange(len(node_lists), dtype=np.uint16), counts
    )
    blob = np.frombuffer(
        b"".join(parts) + b"\x00" * (max_chunks * RATE), dtype=np.uint8
    )
    return blob, meta16


# ---------------------------------------------------------------------------
# host-side layout
# ---------------------------------------------------------------------------


def pack_witness_blob(
    node_lists: Sequence[Sequence[bytes]], max_chunks: int, pad_nodes_to: int | None = None
) -> Tuple[np.ndarray, np.ndarray]:
    """Concatenate per-block node lists into (blob, meta) where meta is the
    (3, B) int32 array of (offsets, lens, block_id) rows.

    The blob gets max_chunks*RATE trailing zeros of gather slack; the node
    axis is padded to `pad_nodes_to` (default: next power of two) with
    zero-length rows so repeated calls reuse a small set of compiled shapes.
    """
    parts: List[bytes] = [n for nodes in node_lists for n in nodes]
    B = len(parts)
    counts = np.fromiter((len(nodes) for nodes in node_lists), np.int64, len(node_lists))
    lens_arr = np.fromiter((len(n) for n in parts), np.int32, B)
    if int(lens_arr.sum()) >= 2**31:
        raise ValueError("witness blob exceeds int32 offset range; split the batch")
    if B and (lens_arr // RATE + 1 > max_chunks).any():
        worst = int(lens_arr.max())
        raise ValueError(f"node of {worst}B exceeds bucket bound {max_chunks}")
    target = pad_nodes_to
    if target is None:
        target = 1
        while target < max(B, 1):
            target *= 2
    if B > target:
        raise ValueError(f"{B} nodes exceed pad_nodes_to={target}")
    meta = np.zeros((3, target), np.int32)
    if B > 1:
        np.cumsum(lens_arr[:-1], out=meta[0, 1:B])
    meta[1, :B] = lens_arr
    meta[2, :B] = np.repeat(np.arange(len(node_lists), dtype=np.int32), counts)
    blob = np.frombuffer(b"".join(parts) + b"\x00" * (max_chunks * RATE), dtype=np.uint8)
    return blob, meta


def roots_to_words(roots: Sequence[bytes]) -> np.ndarray:
    """(NB, 8) u32 little-endian view of 32-byte root hashes."""
    return np.stack([np.frombuffer(r, dtype="<u4") for r in roots])


# --- child-ref extraction (host) ------------------------------------------


def _rlp_item_bounds(data, end: int, pos: int):
    """(kind, payload_start, payload_end, next_pos); kind 0=str, 1=list.
    Mirrors the native scanner (native/packer.cc phant_scan_refs)."""
    b = data[pos]
    if b < 0x80:
        return 0, pos, pos + 1, pos + 1
    if b < 0xB8:
        l, s, kind = b - 0x80, pos + 1, 0
    elif b < 0xC0:
        ll = b - 0xB7
        l = int.from_bytes(bytes(data[pos + 1 : pos + 1 + ll]), "big")
        s, kind = pos + 1 + ll, 0
    elif b < 0xF8:
        l, s, kind = b - 0xC0, pos + 1, 1
    else:
        ll = b - 0xF7
        l = int.from_bytes(bytes(data[pos + 1 : pos + 1 + ll]), "big")
        s, kind = pos + 1 + ll, 1
    if s + l > end:
        raise ValueError("malformed RLP in witness node")
    return kind, s, s + l, s + l


def _scan_list_refs(data, s: int, e: int, out: List[int], depth: int = 0) -> None:
    if depth > 64:
        raise ValueError("RLP nesting too deep")
    items = []
    pos = s
    while pos < e:
        kind, ps, pe, pos = _rlp_item_bounds(data, e, pos)
        items.append((kind, ps, pe))
        if len(items) > 17:
            raise ValueError("not a trie node")
    if len(items) == 17:
        for kind, ps, pe in items[:16]:
            if kind == 0 and pe - ps == 32:
                out.append(ps)
            elif kind == 1 and pe > ps:
                _scan_list_refs(data, ps, pe, out, depth + 1)
    elif len(items) == 2:
        kind0, p0s, p0e = items[0]
        if p0e == p0s:
            raise ValueError("empty hex-prefix path")
        if not (data[p0s] & 0x20):  # extension (leaf bit clear)
            kind, ps, pe = items[1]
            if kind == 0 and pe - ps == 32:
                out.append(ps)
            elif kind == 1:
                _scan_list_refs(data, ps, pe, out, depth + 1)
        else:  # leaf: an account-shaped value commits its storage root
            kind, ps, pe = items[1]
            if kind == 0:
                sr = _account_storage_root_off(data, ps, pe)
                if sr >= 0:
                    out.append(sr)


def _account_storage_root_off(data, s: int, e: int) -> int:
    """Absolute offset of the storage root inside an account-shaped leaf
    value (a 4-string RLP list with 32-byte items 2 and 3), else -1.
    Mirrors native/packer.cc account_storage_root_off."""
    try:
        kind, ps, pe, nxt = _rlp_item_bounds(data, e, s)
    except ValueError:
        return -1
    if kind != 1 or nxt != e:
        return -1
    spans = []
    pos = ps
    while pos < pe:
        try:
            k, ips, ipe, pos = _rlp_item_bounds(data, pe, pos)
        except ValueError:
            return -1
        if k != 0 or len(spans) >= 4:
            return -1
        spans.append((ips, ipe))
    if len(spans) != 4:
        return -1
    if spans[2][1] - spans[2][0] != 32 or spans[3][1] - spans[3][0] != 32:
        return -1
    return spans[2][0]


def scan_refs_py(blob, offsets, lens) -> Tuple[np.ndarray, np.ndarray]:
    """Pure-Python fallback for NativeLib.scan_refs: absolute blob offsets of
    every child hash reference, with the owning node index."""
    ref_off: List[int] = []
    ref_node: List[int] = []
    mv = memoryview(blob) if isinstance(blob, (bytes, bytearray)) else blob
    for i in range(len(offsets)):
        s, e = int(offsets[i]), int(offsets[i]) + int(lens[i])
        kind, ps, pe, pos = _rlp_item_bounds(mv, e, s)
        if kind != 1 or pos != e:
            raise ValueError("witness node is not a single RLP list")
        before = len(ref_off)
        _scan_list_refs(mv, ps, pe, ref_off)
        ref_node.extend([i] * (len(ref_off) - before))
    return np.asarray(ref_off, np.int64), np.asarray(ref_node, np.int32)


def pack_witness(
    node_lists: Sequence[Sequence[bytes]],
    max_chunks: int,
    pad_nodes_to: int | None = None,
    pad_refs_to: int | None = None,
    min_pad: int = 1,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(blob, meta, ref_meta) for `witness_verify_linked`: the blob/meta of
    `pack_witness_blob` plus the (2, R) int32 (ref offset, ref block) rows of
    every child hash reference (native scanner when available, Python
    fallback otherwise). Pad rows carry offset -1. `min_pad` floors both
    padded axes (power-of-two mesh divisibility)."""
    from phant_tpu.utils.native import load_native

    if pad_nodes_to is None and min_pad > 1:
        total = sum(len(nodes) for nodes in node_lists)
        pad_nodes_to = _pow2ceil(max(total, min_pad))
    blob, meta = pack_witness_blob(node_lists, max_chunks, pad_nodes_to)
    counts = [len(nodes) for nodes in node_lists]
    B = sum(counts)
    offsets = meta[0][:B].astype(np.uint64)
    lens = meta[1][:B].astype(np.uint32)
    native = load_native()
    if native is not None:
        ref_off, ref_node = native.scan_refs(blob, offsets, lens)
    else:
        ref_off, ref_node = scan_refs_py(blob, offsets, lens)
    ref_block = meta[2][:B][ref_node]
    R = len(ref_off)
    target = pad_refs_to
    if target is None:
        target = _pow2ceil(max(R, min_pad))
    if R > target:
        raise ValueError(f"{R} refs exceed pad_refs_to={target}")
    ref_meta = np.full((2, target), -1, np.int32)
    ref_meta[0, :R] = ref_off.astype(np.int32)
    ref_meta[1, :R] = ref_block
    return blob, meta, ref_meta
