"""Coalesced sender recovery across serving requests — the sig lane engine.

The paper's stateless hot loop is THREE batched kernels — witness keccak,
post-state-root recomputation, and batched ecrecover over each block's tx
list. The first two ride the batched/pipelined/mesh-sharded serving path
(the witness lane; PR 11's root lane); until this module, sender recovery
did not: every `engine_executeStatelessPayloadV1` paid
`TxSigner.get_senders_batch` synchronously on its handler thread, and the
per-request PHANT_TPU_MIN_ECRECOVER floor (default 64) means a typical
mainnet block (~8-200 txs, usually below the floor) NEVER reaches the
device kernel under serving traffic, no matter how many requests are
concurrently in flight. This engine closes that gap: each request builds
its signature rows `(signing_hash, r, s, recid)` on its own handler
thread (`TxSigner.signature_rows` — host keccak over RLP, embarrassingly
parallel; invalid signatures ride the placeholder lane exactly like
`recover_senders_async`), and the serving scheduler's sig lane hands
concurrent requests' rows here, where they MERGE into ONE device
ecrecover dispatch: K requests' signatures recover in one kernel launch
instead of K sub-floor native batches, and each request gets back its own
sender slice.

THE OFFLOAD-GATE STORY (single source of truth — signer.TxSigner and
stateless.dispatch_sender_recovery point here): the device ecrecover
kernel only wins once the batch amortizes transfer + dispatch latency, so
the same PHANT_TPU_MIN_ECRECOVER floor that gates the per-request path
gates this engine — but applied to the MERGED row count across the
batch's requests. A lone sub-floor request therefore performs zero
merged-dispatch work and lands on the fused native batch (recover +
keccak + address in one FFI call — today's behavior, byte-identical by
construction), and the round-2 invariant — never slower than cpu
end-to-end — survives. Coalescing is what changes the verdict: K blocks'
concatenated tx lists clear the floor no single block can, the exact
below-break-even-alone / wins-when-batched shape that already
rehabilitated witness keccak and the root lane. `device_floor` >= 0
overrides the floor (0 forces the device — the XLA-CPU proxy/tests knob;
the env twin is PHANT_SIG_DEVICE_FLOOR). The device route runs the
Shamir interleaved ladder (`ops/secp256k1_jax.ecrecover_kernel`, the
BENCH-r4-measured production winner; the GLV A/B kernel stays on the
offline `ecrecover_batch_async` path — its host bigint pre-decomposition
does not belong on a serving handler thread).

Protocol: `prefetch_batch` / `begin_batch` / `resolve_batch` /
`abandon_batch` / the fused `sig_many` — deliberately the same names and
semantics as WitnessEngine's two-phase API, so the scheduler's pipeline,
crash paths (handle abandonment), prefetch worker, and mesh lanes drive
this engine through the code path they already drive the witness and
root engines through. The prefetch stage runs the merge LOWERING (row
concatenation + the u256 -> (B,16) u32 limb encode) off the serving
critical path; dispatch enqueues the kernel with ZERO host sync
(HOSTSYNC-scoped); resolve pays the readback. Unlike witness pack blobs
and root merge blobs there is no pooled staging lease: the limb arrays
are a few KB per batch and the limb ENCODE, not the allocation, is the
merge cost — so an abandoned handle strands nothing.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional, Sequence

import numpy as np

from phant_tpu.utils.trace import metrics

#: padding row for the device kernel: (e=1, r=1, s=1, parity=0) — the
#: same filler `ecrecover_batch_async` pads its pow2 buckets with (a
#: well-formed lane whose result is discarded)
_PAD_SCALAR = 1


class SigPrefetch:
    """Output of `SigEngine.prefetch_batch`: the merged rows + limb-packed
    device inputs, computed OFF the serving critical path (the
    scheduler's prefetch worker / a mesh lane's prefetch stage).
    Advisory by identity: `begin_batch(rows_list, prefetch=...)` only
    consumes it when `rows_list` is the SAME list object the merge ran
    over. `release()` exists for crash-path symmetry with the witness and
    root plans; there are no pooled leases to return (idempotent no-op
    beyond dropping the arrays)."""

    __slots__ = ("rows_list", "packed", "n_rows")

    def __init__(self, rows_list, packed, n_rows):
        self.rows_list = rows_list
        self.packed = packed  # (e, r, s, parity) numpy arrays, or None
        self.n_rows = n_rows

    def release(self) -> None:
        self.packed = None


class SigHandle:
    """One in-flight sig batch between `begin_batch` and `resolve_batch`.
    Opaque to callers; `resolved` flips once the senders were returned
    (or the handle was abandoned on a crash path)."""

    __slots__ = (
        "rows_list",
        "n_rows",      # merged signature rows across the batch's requests
        "device_out",  # unresolved (digest_words, valid) device arrays
        "backend",     # "device" | "native" | "scalar"
        "resolved",
    )

    def __init__(self):
        for name in self.__slots__:
            setattr(self, name, None)
        self.n_rows = 0
        self.resolved = False


class SigEngine:
    """Cross-request sender-recovery executor (see module docstring).

    `device_index` pins dispatches to one mesh device — the serving pool
    gives each lane its own pinned SigEngine, so sig batches routed to a
    lane recover on that lane's chip (the witness/root-engine pinning
    model). `device_floor`: -1 (default) = the PHANT_TPU_MIN_ECRECOVER
    floor applied to the MERGED row count (resolved ONCE here, never on
    the hot path); 0 forces the device route (tests / XLA-CPU proxy);
    > 0 is a fixed merged-row floor. Thread-safe: stats under `_lock`;
    merge/dispatch/resolve touch no shared tables (rows are
    caller-owned)."""

    def __init__(
        self,
        device_floor: Optional[int] = None,
        device_index: Optional[int] = None,
    ):
        if device_floor is None:
            device_floor = int(os.environ.get("PHANT_SIG_DEVICE_FLOOR", "-1"))
        self._device_floor = device_floor
        # the per-call env re-read this engine replaces (signer.py r14
        # bugfix): the floor is a process-lifetime deployment knob,
        # resolved once per engine
        self._min_device = int(os.environ.get("PHANT_TPU_MIN_ECRECOVER", "64"))
        self._device_index = device_index
        self._pinned = None
        self._lock = threading.Lock()
        self.stats = {
            "sig_batches": 0,
            "sig_requests": 0,
            "sig_rows": 0,
            "device_batches": 0,
            "native_batches": 0,
            "scalar_batches": 0,
        }

    # -- routing --------------------------------------------------------------

    def _pinned_device(self):
        if self._device_index is None:
            return None
        if self._pinned is None:
            import jax

            devices = jax.devices()
            self._pinned = devices[self._device_index % len(devices)]
        return self._pinned

    @staticmethod
    def _n_rows(rows_list: Sequence) -> int:
        return sum(r.n for r in rows_list)

    def _route_device(self, n_rows: int) -> bool:
        """THE routing predicate (see the module docstring's offload-gate
        story): device iff a device exists and the MERGED row count
        clears the ecrecover floor — a lone sub-floor request keeps the
        fused native batch. With NO native toolchain a sub-floor batch
        still promotes to the device (the kernel beats scalar Python
        even below the floor — the floor only arbitrates device vs the
        fused NATIVE batch, the same promotion `recover_rows_async`
        applies; without it the lane would be slower than the inline
        path it replaced on toolchain-less TPU deployments)."""
        from phant_tpu.backend import crypto_backend, jax_device_ok

        if n_rows == 0:
            return False
        if crypto_backend() != "tpu" or not jax_device_ok():
            return False
        floor = (
            self._device_floor if self._device_floor >= 0 else self._min_device
        )
        if n_rows >= floor:
            return True
        from phant_tpu.utils.native import load_native

        return load_native() is None

    # -- merge (the row-lowering stage) ---------------------------------------

    @staticmethod
    def _merge(rows_list: Sequence):
        """(e, r, s, parity) device-kernel inputs for the batch's merged
        rows, pow2-bucket-padded so repeat batches land on a handful of
        compiled shapes (ops/secp256k1_jax._bucket_pad — the same shape
        discipline as `ecrecover_batch_async`). Pure host work: list
        concatenation + the u256 -> limb encode."""
        from phant_tpu.ops.secp256k1_jax import _bucket_pad, ints_to_limbs

        msgs: List[bytes] = []
        rs: List[int] = []
        ss: List[int] = []
        pars: List[int] = []
        for rows in rows_list:
            msgs.extend(rows.msgs)
            rs.extend(rows.rs)
            ss.extend(rows.ss)
            pars.extend(rid & 1 for rid in rows.recids)
        pad = _bucket_pad(len(msgs)) - len(msgs)
        e = ints_to_limbs(
            [int.from_bytes(m, "big") for m in msgs] + [_PAD_SCALAR] * pad
        )
        r = ints_to_limbs(rs + [_PAD_SCALAR] * pad)
        s = ints_to_limbs(ss + [_PAD_SCALAR] * pad)
        par = np.array(pars + [0] * pad, np.uint32)
        return e, r, s, par

    # -- two-phase protocol (scheduler pipeline shape) ------------------------

    def prefetch_batch(self, rows_list: Sequence) -> SigPrefetch:
        """STAGE 0 for sig batches: run the merge (row concat + limb
        encode) off the serving critical path. Identity-advisory — pass
        the SAME rows list to `begin_batch(rows_list, prefetch=...)`."""
        with metrics.phase("witness_engine.sig_prefetch"):
            n_rows = self._n_rows(rows_list)
            if not self._route_device(n_rows):
                # host route: a limb pack would go unused — carry only
                # the row count (begin_batch re-checks and routes host)
                return SigPrefetch(rows_list, None, n_rows)
            return SigPrefetch(rows_list, self._merge(rows_list), n_rows)

    def begin_batch(
        self, rows_list: Sequence, prefetch: Optional[SigPrefetch] = None
    ) -> SigHandle:
        """Pack + dispatch one sig batch with no host sync: route by the
        offload gate, merge (or consume the prefetch merge), and enqueue
        the ecrecover kernel. Everything that needs the senders waits for
        `resolve_batch` (host routes run their fused native batch
        there, off the executor thread)."""
        pf = prefetch
        if pf is not None and pf.rows_list is not rows_list:
            pf.release()  # not the batch this merge was computed for
            pf = None
            metrics.count("witness_engine.sig_plan_stale")
        h = SigHandle()
        h.rows_list = list(rows_list)
        with metrics.phase("witness_engine.sig_pack"):
            h.n_rows = pf.n_rows if pf is not None else self._n_rows(rows_list)
            route = self._route_device(h.n_rows)
            packed = None
            if route:
                if pf is not None and pf.packed is not None:
                    packed = pf.packed
                    pf.packed = None  # ownership moves
                    metrics.count("witness_engine.sig_plan_hits")
                else:
                    packed = self._merge(rows_list)
            else:
                h.backend = "host"  # native vs scalar classified at resolve
                if pf is not None:
                    pf.release()
        if route:
            with metrics.phase("witness_engine.sig_dispatch"):
                try:
                    h.device_out = self._dispatch(packed)
                    h.backend = "device"
                except Exception:
                    import logging

                    logging.getLogger("phant.sig").warning(
                        "device sig dispatch failed for %d rows; "
                        "native fallback at resolve",
                        h.n_rows,
                        exc_info=True,
                    )
                    h.backend = "host"
        return h

    def _dispatch(self, packed):
        """Enqueue the merged ecrecover on the (possibly pinned) device —
        upload + kernel launch, ZERO host sync; returns the unresolved
        (digest_words, valid) device arrays."""
        import jax
        import jax.numpy as jnp

        from phant_tpu.ops.secp256k1_jax import ecrecover_kernel

        e, r, s, par = packed
        device = self._pinned_device()
        if device is not None:
            # committed inputs pin the compute with them (mesh lanes)
            args = tuple(jax.device_put(a, device) for a in (e, r, s, par))
        else:
            args = tuple(jnp.asarray(a) for a in (e, r, s, par))  # phantlint: disable=JNPHOSTLOOP — fixed 4-argument upload tuple, not a per-row loop
        return ecrecover_kernel(*args)

    def resolve_batch(self, handle: SigHandle) -> List[List[Optional[bytes]]]:
        """Per-request sender slices (tx order within each request; None =
        invalid signature — the caller raises with the right per-block
        attribution, `blockchain.chain.apply_body`). Device: the address
        readback is the honest sync; host: the fused native batch over
        the SAME merged rows (one FFI call for K requests — still
        coalesced), or the scalar pure-Python path when no toolchain is
        present. Byte-identical across routes by construction
        (differential-tested)."""
        if handle.resolved:
            raise RuntimeError("sig handle already resolved")
        try:
            with metrics.phase("witness_engine.sig_resolve"):
                if handle.backend == "device":
                    flat = self._resolve_device(handle)
                else:
                    flat = self._resolve_host(handle)
                out: List[List[Optional[bytes]]] = []
                pos = 0
                # merged rows concatenate per request in order; the bad
                # (placeholder-lane) mask re-applies per request
                for rows in handle.rows_list:
                    senders = flat[pos : pos + rows.n]
                    pos += rows.n
                    if rows.bad:
                        senders = [
                            None if i in rows.bad else a
                            for i, a in enumerate(senders)
                        ]
                    out.append(senders)
        except BaseException:
            self.abandon_batch(handle)
            raise
        handle.resolved = True
        n = len(handle.rows_list)
        backend = handle.backend or "native"
        handle.device_out = None
        with self._lock:
            self.stats["sig_batches"] += 1
            self.stats["sig_requests"] += n
            self.stats["sig_rows"] += handle.n_rows
            self.stats[backend + "_batches"] += 1
        metrics.count("witness_engine.sig_batches", backend=backend)
        metrics.count("witness_engine.sig_requests", n)
        metrics.count("witness_engine.sig_rows", handle.n_rows)
        return out

    @staticmethod
    def _resolve_device(handle: SigHandle) -> List[Optional[bytes]]:
        from phant_tpu.ops.secp256k1_jax import digest_words_to_addresses

        digest, valid = handle.device_out
        addrs = digest_words_to_addresses(np.asarray(digest))  # phantlint: disable=HOSTSYNC — timed sender readback is the product
        valid_np = np.asarray(valid)  # phantlint: disable=HOSTSYNC — timed sender readback is the product
        return [
            addrs[k] if bool(valid_np[k]) else None
            for k in range(handle.n_rows)
        ]

    @staticmethod
    def _resolve_host(handle: SigHandle) -> List[Optional[bytes]]:
        """The offload-gated host route over the SAME merged rows — one
        fused native batch for K requests, or the scalar fallback. The
        recovery itself is `signer.recover_rows_host`, THE shared
        definition the local `recover_rows_async` path uses too (the
        byte-identity contract rides on there being exactly one). The
        backend classification lands on the handle so batch records and
        the lone-request gate read which path actually ran."""
        from phant_tpu.signer.signer import recover_rows_host

        msgs: List[bytes] = []
        rs: List[int] = []
        ss: List[int] = []
        rids: List[int] = []
        for rows in handle.rows_list:
            msgs.extend(rows.msgs)
            rs.extend(rows.rs)
            ss.extend(rows.ss)
            rids.extend(rows.recids)
        out, handle.backend = recover_rows_host(msgs, rs, ss, rids)
        return out

    def abandon_batch(self, handle: SigHandle) -> None:
        """Release a handle WITHOUT resolving it — the crash path. No
        pooled leases back this engine (see the module docstring), so
        abandonment only retires the handle; an enqueued device dispatch
        completes into garbage-collected arrays. Idempotent."""
        if handle.resolved:
            return
        handle.resolved = True
        handle.device_out = None
        handle.rows_list = []

    # -- fused one-call face ---------------------------------------------------

    def sig_many(self, rows_list: Sequence) -> List[List[Optional[bytes]]]:
        """K requests' sender slices in one engine call — begin + resolve
        fused (the depth-1 scheduler path and the offline bench face)."""
        return self.resolve_batch(self.begin_batch(rows_list))

    def stats_snapshot(self) -> dict:
        with self._lock:
            return dict(self.stats)


_shared: Optional[SigEngine] = None
_shared_lock = threading.Lock()


def shared_sig_engine() -> SigEngine:
    """Process-global sig engine (the scheduler default — signature rows
    carry no cross-request state, so one engine serves any number of
    schedulers)."""
    global _shared
    with _shared_lock:
        if _shared is None:
            _shared = SigEngine()
        return _shared
