"""Batched post-state-root recomputation across serving requests.

The paper's stateless hot loop is TWO batched kernels — witness keccak and
post-state-root recomputation — but until this module only the first ever
rode the batched/pipelined/mesh-sharded serving path: every
`engine_executeStatelessPayloadV1` paid its post root as serial host
Python (`WitnessStateDB.state_root()` — keccak per node, per request, per
storage trie). This engine closes that gap: each request builds ONE fused
account+storage `HashPlan` on its own handler thread
(stateless.WitnessStateDB.post_root_plan — host structural work,
embarrassingly parallel), and the serving scheduler's root lane hands
concurrent requests' plans here, where they MERGE into one level-aligned
device program (ops/mpt_jax.merge_plans + `_hash_plan_outputs`): K
requests' dirty subtrees hash in max(depth) sequential keccak rounds and
one dispatch instead of K host walks.

THE OFFLOAD-GATE STORY (single source of truth — stateless.PartialTrie
and mpt.trie_root_hash point here): a post-root re-hash ships template
bytes to the device and reads 32 B/root back, so the decision is the same
link-aware cost model every other hashing route uses
(backend.device_offload_pays — upload + round trip must beat hashing the
same bytes natively). One witness subtree is a few hundred nodes, BELOW
the break-even alone: a lone request therefore keeps the host walk, and
the round-2 invariant — never slower than cpu end-to-end — survives by
construction. Coalescing is what changes the verdict: the merged payload
of a full batch clears the bar the way a single request cannot, the exact
below-break-even-alone / wins-when-batched shape cross-request coalescing
already rehabilitated for witness keccak. `device_floor` >= 0 overrides
the model (0 forces the device — the XLA-CPU proxy/tests knob; the env
twin is PHANT_ROOT_DEVICE_FLOOR).

Protocol: `prefetch_batch` / `begin_batch` / `resolve_batch` /
`abandon_batch` / the fused `root_many` — deliberately the same names and
semantics as WitnessEngine's two-phase API, so the scheduler's pipeline,
crash paths (handle abandonment), prefetch worker, and mesh lanes drive
either engine through one code path. Dispatch enqueues with ZERO host
sync (HOSTSYNC-scoped); resolve pays the readback. Merged staging blobs
lease from the same process-global pool as witness staging
(witness_engine._staging), keyed by pow2 size, returned at resolve (or
abandon) exactly like witness pack leases.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from phant_tpu.utils.trace import metrics


class RootPrefetch:
    """Output of `RootEngine.prefetch_batch`: the merged plan + filled
    staging lease, computed OFF the serving critical path (the scheduler's
    prefetch worker / a mesh lane's prefetch stage). Advisory by identity:
    `begin_batch(plans, prefetch=...)` only consumes it when `plans` is
    the SAME list object the merge ran over; anything else releases it.
    `release()` is idempotent (consumption nulls the lease)."""

    __slots__ = ("plans", "merged", "outs", "lease", "payload")

    def __init__(self, plans, merged, outs, lease, payload):
        self.plans = plans
        self.merged = merged
        self.outs = outs
        self.lease = lease  # (key, entry) from the shared staging pool
        self.payload = payload

    def release(self) -> None:
        if self.lease is not None:
            from phant_tpu.ops.witness_engine import _staging

            key, entry = self.lease
            self.lease = self.merged = self.outs = None
            _staging.give(key, entry)


class RootHandle:
    """One in-flight root batch between `begin_batch` and `resolve_batch`.
    Opaque to callers; `resolved` flips once the digests were returned
    (or the handle was abandoned on a crash path)."""

    __slots__ = (
        "plans",
        "merged",
        "outs",        # per-plan merged out rows (device route)
        "lease",
        "device_out",  # unresolved (Rp, 8) u32 device array
        "backend",     # "device" | "host"
        "payload",
        "resolved",
    )

    def __init__(self):
        for name in self.__slots__:
            setattr(self, name, None)
        self.resolved = False


class RootEngine:
    """Cross-request post-root executor (see module docstring).

    `device_index` pins dispatches to one mesh device — the serving pool
    gives each lane its own pinned RootEngine, so root batches routed to
    a lane hash on that lane's chip (the witness-engine pinning model).
    `device_floor`: -1 (default) = the adaptive link-aware gate; 0 forces
    the device route (tests / XLA-CPU proxy); > 0 is a fixed payload-byte
    floor. Thread-safe: stats under `_lock`; merge/dispatch/resolve touch
    no shared tables (plans are caller-owned)."""

    def __init__(
        self,
        device_floor: Optional[int] = None,
        device_index: Optional[int] = None,
    ):
        if device_floor is None:
            device_floor = int(os.environ.get("PHANT_ROOT_DEVICE_FLOOR", "-1"))
        self._device_floor = device_floor
        self._device_index = device_index
        self._pinned = None
        self._lock = threading.Lock()
        self.stats = {
            "root_batches": 0,
            "root_requests": 0,
            "device_batches": 0,
            "host_batches": 0,
        }

    # -- routing --------------------------------------------------------------

    def _pinned_device(self):
        if self._device_index is None:
            return None
        if self._pinned is None:
            import jax

            devices = jax.devices()
            self._pinned = devices[self._device_index % len(devices)]
        return self._pinned

    @staticmethod
    def _payload_bytes(plans: Sequence) -> int:
        """Total template bytes across the batch — the shippable payload
        the offload gate weighs (ops/mpt_jax.plan_payload_bytes, the one
        definition the scheduler's byte accounting shares)."""
        from phant_tpu.ops.mpt_jax import plan_payload_bytes

        return sum(plan_payload_bytes(p) for p in plans)

    def _route_device(self, payload: int) -> bool:
        """THE routing predicate (see the module docstring's offload-gate
        story): device iff a device exists and the merged payload clears
        the link-aware break-even — a lone sub-break-even request keeps
        the host walk."""
        from phant_tpu.backend import (
            crypto_backend,
            device_offload_pays,
            jax_device_ok,
        )

        if crypto_backend() != "tpu" or not jax_device_ok():
            return False
        if self._device_floor >= 0:
            return payload >= self._device_floor
        return device_offload_pays(payload)

    # -- merge (the plan-lowering stage) --------------------------------------

    def _merge(self, plans: Sequence) -> Tuple[object, list, tuple, int]:
        """(merged plan, per-plan out rows, staging lease, payload):
        concatenate the batch's plans into one level-aligned program over
        a pooled blob (ops/mpt_jax.merge_plans)."""
        from phant_tpu.crypto.keccak import RATE
        from phant_tpu.ops.mpt_jax import MPT_MAX_CHUNKS, _pow2, merge_plans
        from phant_tpu.ops.witness_engine import _staging

        payload = self._payload_bytes(plans)
        raw = sum(len(p.blob) for p in plans)
        # the SAME pow2 merge_plans sizes its blob with — the pooled
        # lease can never come up short
        need = _pow2(raw + MPT_MAX_CHUNKS * RATE)
        key = ("root_blob", need)
        entry = _staging.take(key)
        if entry is None:
            entry = {"blob": np.zeros(need, np.uint8), "dirty": 0}
        blob = entry["blob"]
        if entry["dirty"] > raw:
            blob[raw : entry["dirty"]] = 0
        entry["dirty"] = raw
        merged, outs = merge_plans(plans, blob_out=blob)
        return merged, outs, (key, entry), payload

    # -- two-phase protocol (scheduler pipeline shape) ------------------------

    def prefetch_batch(self, plans: Sequence) -> RootPrefetch:
        """STAGE 0 for root batches: run the merge (host memcpy + index
        remap work) off the serving critical path. Identity-advisory —
        pass the SAME plans list to `begin_batch(plans, prefetch=...)`;
        an unused plan must be `release()`d."""
        with metrics.phase("witness_engine.root_prefetch"):
            payload = self._payload_bytes(plans)
            if not self._route_device(payload):
                # host route: a merge would go unused — carry only the
                # payload verdict (begin_batch re-checks and routes host)
                return RootPrefetch(plans, None, None, None, payload)
            merged, outs, lease, payload = self._merge(plans)
            return RootPrefetch(plans, merged, outs, lease, payload)

    def begin_batch(
        self, plans: Sequence, prefetch: Optional[RootPrefetch] = None
    ) -> RootHandle:
        """Pack + dispatch one root batch with no host sync: route by the
        offload gate, merge (or consume the prefetch merge), and enqueue
        the fused device program. Everything that needs the digests waits
        for `resolve_batch`."""
        pf = prefetch
        if pf is not None and pf.plans is not plans:
            pf.release()  # not the batch this merge was computed for
            pf = None
            metrics.count("witness_engine.root_plan_stale")
        h = RootHandle()
        h.plans = list(plans)
        with metrics.phase("witness_engine.root_pack"):
            h.payload = pf.payload if pf is not None else self._payload_bytes(plans)
            route = self._route_device(h.payload)
            if route:
                if pf is not None and pf.merged is not None:
                    h.merged, h.outs, h.lease = pf.merged, pf.outs, pf.lease
                    pf.lease = pf.merged = pf.outs = None  # ownership moves
                    metrics.count("witness_engine.root_plan_hits")
                else:
                    h.merged, h.outs, h.lease, _ = self._merge(plans)
            else:
                h.backend = "host"
                if pf is not None:
                    pf.release()  # host route: the merge goes unused
        if route:
            with metrics.phase("witness_engine.root_dispatch"):
                try:
                    h.device_out = self._dispatch(h.merged)
                    h.backend = "device"
                except Exception:
                    import logging

                    logging.getLogger("phant.root").warning(
                        "device root dispatch failed for %d plans; "
                        "host fallback at resolve",
                        len(plans),
                        exc_info=True,
                    )
                    self._release_lease(h)
                    h.backend = "host"
        return h

    def _dispatch(self, merged):
        """Enqueue the merged program on the (possibly pinned) device —
        upload + kernel launch, ZERO host sync; returns the unresolved
        (Rp, 8) u32 output array."""
        import jax
        import jax.numpy as jnp

        from phant_tpu.ops.mpt_jax import (
            MPT_MAX_CHUNKS,
            _hash_plan_outputs,
            _pow2,
        )

        out_rows = merged.out_rows
        rp = _pow2(len(out_rows))
        padded = np.full(rp, out_rows[-1], np.int32)
        padded[: len(out_rows)] = out_rows
        device = self._pinned_device()
        if device is not None:
            # committed inputs pin the compute with them (mesh lanes)
            blob_d = jax.device_put(merged.blob, device)
            rows_d = jax.device_put(padded, device)
            levels_d = tuple(
                tuple(jax.device_put(a, device) for a in lvl)  # phantlint: disable=JNPHOSTLOOP — bounded per-level metadata upload
                for lvl in merged.levels
            )
        else:
            blob_d = jnp.asarray(merged.blob)
            rows_d = jnp.asarray(padded)
            levels_d = tuple(
                tuple(jnp.asarray(a) for a in lvl) for lvl in merged.levels  # phantlint: disable=JNPHOSTLOOP — bounded per-level metadata upload
            )
        return _hash_plan_outputs(
            blob_d, levels_d, rows_d, max_chunks=MPT_MAX_CHUNKS
        )

    def resolve_batch(self, handle: RootHandle) -> List[List[bytes]]:
        """Per-plan out-row digests (each plan's storage roots in patch
        order, its post root LAST — `HashPlan.out_rows` order). Device:
        the readback is the honest sync; host: the per-plan CPU mirror
        (execute_plan_outputs_host), byte-identical by construction."""
        if handle.resolved:
            raise RuntimeError("root handle already resolved")
        try:
            with metrics.phase("witness_engine.root_resolve"):
                if handle.backend == "device":
                    arr = np.asarray(handle.device_out, dtype="<u4")  # phantlint: disable=HOSTSYNC — timed root readback is the product
                    flat = [arr[k].tobytes() for k in range(arr.shape[0])]
                    out: List[List[bytes]] = []
                    pos = 0
                    # merged out rows concatenate per plan in order
                    for rows in handle.outs:
                        out.append(flat[pos : pos + len(rows)])
                        pos += len(rows)
                else:
                    from phant_tpu.ops.mpt_jax import execute_plan_outputs_host

                    out = [
                        execute_plan_outputs_host(p) for p in handle.plans
                    ]
        except BaseException:
            self.abandon_batch(handle)
            raise
        handle.resolved = True
        self._release_lease(handle)
        n = len(handle.plans)
        backend = handle.backend or "host"
        with self._lock:
            self.stats["root_batches"] += 1
            self.stats["root_requests"] += n
            self.stats[backend + "_batches"] += 1
        metrics.count("witness_engine.root_batches", backend=backend)
        metrics.count("witness_engine.root_requests", n)
        return out

    def abandon_batch(self, handle: RootHandle) -> None:
        """Release a handle WITHOUT resolving it — the crash path. A
        device lease stays stranded when a dispatch may still be reading
        it (the witness-engine contract: bounded loss on a crash path);
        an undispatched merge lease returns to the pool. Idempotent."""
        if handle.resolved:
            return
        handle.resolved = True
        if handle.device_out is None:
            self._release_lease(handle)
        handle.device_out = None
        handle.plans = []

    @staticmethod
    def _release_lease(handle: RootHandle) -> None:
        if handle.lease is not None:
            from phant_tpu.ops.witness_engine import _staging

            key, entry = handle.lease
            handle.lease = handle.merged = None
            _staging.give(key, entry)

    # -- fused one-call face ---------------------------------------------------

    def root_many(self, plans: Sequence) -> List[List[bytes]]:
        """K requests' out digests in one engine call — begin + resolve
        fused (the depth-1 scheduler path and the offline bench face)."""
        return self.resolve_batch(self.begin_batch(plans))

    def stats_snapshot(self) -> dict:
        with self._lock:
            return dict(self.stats)


_shared: Optional[RootEngine] = None
_shared_lock = threading.Lock()


def shared_root_engine() -> RootEngine:
    """Process-global root engine (the scheduler default — plans carry no
    cross-request state, so one engine serves any number of schedulers)."""
    global _shared
    with _shared_lock:
        if _shared is None:
            _shared = RootEngine()
        return _shared
