"""Device (TPU) kernels for the stateless-validation hot loop.

Importing this package enables the persistent XLA compilation cache so the
expensive kernels (ecrecover ladder, keccak) compile once per machine.
"""

from phant_tpu.ops._cache import enable_compilation_cache

enable_compilation_cache()
