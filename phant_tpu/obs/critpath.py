"""Per-request critical-path latency attribution (PR 15).

The serving stack emits rich counters and spans (PRs 1/4), but nothing
reconstructs where a REQUEST's wall clock actually went: the
`verify_block` span carries its phase timers and the batch records the
serving lanes attach, yet "queue wait vs dispatch vs resolve vs EVM" had
to be eyeballed per trace line. This module closes that gap with a span
sink that, at every top-level `verify_block` close, TILES the request's
wall clock into an exclusive phase breakdown:

  sig_rows       signature-row build on the handler thread
  queue_wait     admission -> executor pickup (witness batch record)
  prefetch       waiting on the 4th-stage decode/pre-scan plan
  pack           begin_batch's lock-held scan (the batch's pack_ms)
  dispatch       dispatched-and-in-flight: begin_batch return -> resolve
                 start — the window the device (or the pipeline ahead of
                 this batch) owns the request
  resolve        readback + commit + linkage join (the batch's resolve_ms)
  witness_decode witness -> WitnessStateDB materialization
  sig_wait       the sig-lane join block before EVM execution
  evm            block execution minus the sig join
  root_plan      fused post-root hash-plan build on the handler thread
  root_wait      root-lane queue wait (root batch record)
  post_root      the rest of the post-root phase: merged dispatch +
                 readback + apply, or the host walk

The tiling is HIERARCHICAL and clipped: batch-record stage timings are
clipped into the request-side phase that contains them (a stage number
can never claim more than the request actually waited), and each level's
remainder goes to the enclosing catch-all (`dispatch` inside
witness_verify, `evm` inside execute, `post_root` inside the post-root
phase) — so the sub-tilings sum EXACTLY to their parent phases and the
only unattributed residual is real: span overhead and gaps between
phases. That residual is the honesty check: `critpath.unattributed_pct`
(and the coverage twin) gauge the cumulative attributed share, and the
test suite asserts >= 95% on the serving path at pipeline depths 1 AND 2
across all three engine lanes. Everything lands in the
`critpath.phase_seconds{phase=}` histogram family, which the derived
p50/p99 gauges (utils/trace.py prometheus_text) turn into per-phase
quantiles at scrape time.

SLO exemplars: metrics tell you THAT requests are slow; the exemplar
shows WHY. A request whose wall clock exceeds `--slo-budget-ms`
(PHANT_SLO_BUDGET_MS; 0/unset = off) — or whose single phase exceeds a
per-phase override (PHANT_SLO_BUDGET_MS_<PHASE>, e.g.
PHANT_SLO_BUDGET_MS_QUEUE_WAIT) — is captured as its FULL span tree plus
the breakdown into a dedicated bounded flight ring, served at
`GET /debug/slow` and counted in `obs.slow_captures{trigger=}`.

Near-budget tier (PR 16, closing PR 15's named open): on a healthy
server the violation ring is EMPTY — there is nothing to read when an
operator asks "what do our slowest-but-passing requests look like". A
request that lands in the top `PHANT_SLO_NEAR_PCT` percent of the
budget (wall > budget * (1 - near_pct/100) without blowing it) is
captured at a sampled 1-in-`PHANT_SLO_NEAR_SAMPLE_N` rate with
`trigger=near`; its `over_ms` is NEGATIVE — the remaining headroom.
The sampler's RNG is injectable via `configure(near_rng=...)` so tests
pin the decision sequence.

Config is resolved ONCE from the environment and memoized (the env-read-
per-request pattern is exactly what the PR 14 signer bugfix removed from
the hot path); `refresh_from_env()` re-reads it (the Engine API server
calls it at construction, after the CLI has written its flags into the
env), and `configure()` overrides it directly (tests, the bench A/B).
`PHANT_OBS_ATTRIBUTION=0` disables the whole layer — the off leg of the
`obs_overhead` bench section.

Thread-safety: the rollup runs on request threads; the cumulative
coverage totals sit under one small lock, the metrics registry and the
slow ring carry their own. The sink must never fail the traced work —
span() already swallows sink exceptions, and the rollup additionally
treats malformed records as zero-valued.
"""

from __future__ import annotations

import os
import random
import threading
from typing import Dict, Optional, Tuple

from phant_tpu.obs.flight import FlightRecorder
from phant_tpu.utils.trace import metrics

#: the closed phase vocabulary (documented above + in METRIC_HELP):
#: `critpath.phase_seconds{phase=}` only ever carries these labels, so
#: the family's cardinality is bounded by construction
PHASES: Tuple[str, ...] = (
    "sig_rows",
    "queue_wait",
    "prefetch",
    "pack",
    "dispatch",
    "resolve",
    "witness_decode",
    "sig_wait",
    "evm",
    "root_plan",
    "root_wait",
    "post_root",
)

#: the dedicated slow-exemplar ring (served at GET /debug/slow): its own
#: recorder so a burst of slow requests cannot wash the main flight ring's
#: scheduler postmortem context away — and vice versa
slow = FlightRecorder(
    capacity=int(os.environ.get("PHANT_SLOW_CAPACITY", "64"))
)


class _Config:
    __slots__ = (
        "enabled",
        "budget_ms",
        "phase_budgets_ms",
        "near_pct",
        "near_sample_n",
    )

    def __init__(
        self,
        enabled: bool,
        budget_ms: float,
        phase_budgets_ms: Dict[str, float],
        near_pct: float,
        near_sample_n: int,
    ):
        self.enabled = enabled
        self.budget_ms = budget_ms
        self.phase_budgets_ms = phase_budgets_ms
        self.near_pct = near_pct
        self.near_sample_n = near_sample_n


def _env_num(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)) or str(default))
    except ValueError:
        return default


def _config_from_env() -> _Config:
    budget = _env_num("PHANT_SLO_BUDGET_MS", 0.0)
    phase_budgets: Dict[str, float] = {}
    for ph in PHASES:
        raw = os.environ.get(f"PHANT_SLO_BUDGET_MS_{ph.upper()}")
        if not raw:
            continue
        try:
            v = float(raw)
        except ValueError:
            continue
        if v > 0:
            phase_budgets[ph] = v
    return _Config(
        enabled=os.environ.get("PHANT_OBS_ATTRIBUTION", "1") not in ("0", ""),
        budget_ms=budget,
        phase_budgets_ms=phase_budgets,
        near_pct=min(max(_env_num("PHANT_SLO_NEAR_PCT", 0.0), 0.0), 100.0),
        near_sample_n=max(int(_env_num("PHANT_SLO_NEAR_SAMPLE_N", 8.0)), 0),
    )


_cfg: _Config = _config_from_env()
_cfg_lock = threading.Lock()

#: near-budget tier sampler; tests pin it via configure(near_rng=...)
_near_rng = random.Random()


def refresh_from_env() -> None:
    """Re-resolve the memoized config from the environment (the Engine API
    server calls this at construction so `--slo-budget-ms`/env changes
    made before boot take effect; tests call it after monkeypatching)."""
    global _cfg
    with _cfg_lock:
        _cfg = _config_from_env()


def configure(
    enabled: Optional[bool] = None,
    budget_ms: Optional[float] = None,
    phase_budgets_ms: Optional[Dict[str, float]] = None,
    near_pct: Optional[float] = None,
    near_sample_n: Optional[int] = None,
    near_rng: Optional[random.Random] = None,
) -> None:
    """Override the memoized config directly (tests, the bench A/B legs);
    None leaves a field as-is. `near_rng` replaces the near-tier sampler's
    generator (determinism for tests)."""
    global _cfg, _near_rng
    with _cfg_lock:
        _cfg = _Config(
            enabled=_cfg.enabled if enabled is None else enabled,
            budget_ms=_cfg.budget_ms if budget_ms is None else budget_ms,
            phase_budgets_ms=(
                dict(_cfg.phase_budgets_ms)
                if phase_budgets_ms is None
                else dict(phase_budgets_ms)
            ),
            near_pct=_cfg.near_pct if near_pct is None else near_pct,
            near_sample_n=(
                _cfg.near_sample_n
                if near_sample_n is None
                else max(int(near_sample_n), 0)
            ),
        )
        if near_rng is not None:
            _near_rng = near_rng


def enabled() -> bool:
    """Is the attribution layer on? Read at scheduler/pool construction to
    gate the busy accountants (obs/busy.py) with the same switch."""
    return _cfg.enabled


def budget_ms() -> float:
    """The wall-clock SLO budget (0 = exemplar capture off)."""
    return _cfg.budget_ms


# cumulative coverage totals (the honesty gauges' numerator/denominator);
# guarded by one small lock — two floats, nothing more
_tot_lock = threading.Lock()
_tot_wall_s = 0.0
_tot_attr_s = 0.0


def totals() -> Tuple[float, float]:
    """(wall_s, attributed_s) cumulative since process start / last reset —
    the bench section and tests compute coverage over a window from the
    delta of two calls."""
    with _tot_lock:
        return _tot_wall_s, _tot_attr_s


def reset_totals() -> None:
    global _tot_wall_s, _tot_attr_s
    with _tot_lock:
        _tot_wall_s = 0.0
        _tot_attr_s = 0.0


def _num(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) and v == v else None


def attribute(record: dict) -> Tuple[Dict[str, float], float, float]:
    """(breakdown_ms, unattributed_ms, wall_ms) for one top-level
    `verify_block` span record. Pure function of the record — the
    unit-testable core of the rollup.

    Tiling rules (see the module docstring for the phase meanings):
    every batch-record stage timing is clipped into the remaining width
    of the request-side phase that contains it, in pipeline order, and
    the remainder goes to that level's catch-all — so the sub-tilings
    sum exactly to their parent phases and attributed time can never
    exceed the phases the request actually measured."""
    wall = _num(record.get("duration_ms")) or 0.0
    phases = record.get("phases") or {}

    def ph(name: str) -> float:
        st = phases.get(name)
        if isinstance(st, dict):
            return _num(st.get("total_ms")) or 0.0
        return 0.0

    out: Dict[str, float] = {}

    def put(name: str, v: float) -> None:
        if v > 0.0:
            out[name] = out.get(name, 0.0) + v

    # handler-thread phases, already exclusive by construction
    put("sig_rows", ph("stateless.sig_rows"))
    put("witness_decode", ph("stateless.witness_decode"))

    # witness_verify sub-tiling: queue_wait/prefetch/pack/resolve come
    # from the witness batch record (bare keys — the sig/root lanes
    # prefix theirs), each clipped to what is left of the phase; the
    # remainder is `dispatch`, the dispatched-and-in-flight window
    wv = ph("stateless.witness_verify")
    rem = wv
    for label, key in (
        ("queue_wait", "queue_wait_ms"),
        ("prefetch", "prefetch_ms"),
        ("pack", "pack_ms"),
        ("resolve", "resolve_ms"),
    ):
        v = _num(record.get(key))
        if v is not None and v > 0.0:
            v = min(v, rem)
            put(label, v)
            rem -= v
    put("dispatch", rem)

    # execute sub-tiling: the sig-lane join block, then EVM proper
    ex = ph("stateless.execute")
    sw = min(ph("sched.sig_wait"), ex)
    put("sig_wait", sw)
    put("evm", ex - sw)

    # post-root sub-tiling: plan build (its own nested phase), the
    # root-lane queue wait (prefixed record key), remainder = the merged
    # dispatch + readback + apply, or the host walk
    pr = ph("stateless.post_root")
    rp = min(ph("stateless.post_root_plan"), pr)
    rw = _num(record.get("root_queue_wait_ms")) or 0.0
    rw = min(max(rw, 0.0), pr - rp)
    put("root_plan", rp)
    put("root_wait", rw)
    put("post_root", pr - rp - rw)

    attributed = sum(out.values())
    unattributed = max(0.0, wall - attributed)
    return out, unattributed, wall


def _capture_slow(
    record: dict,
    breakdown: Dict[str, float],
    wall_ms: float,
    trigger: str,
    budget: float,
    over_ms: float,
) -> None:
    slow.record(
        "obs.slow_capture",
        trigger=trigger,
        budget_ms=budget,
        wall_ms=wall_ms,
        over_ms=round(over_ms, 3),
        breakdown_ms={k: round(v, 3) for k, v in breakdown.items()},
        span=record,
        trace_id=record.get("trace_id"),
    )
    metrics.count("obs.slow_captures", trigger=trigger)


def rollup(record: dict) -> None:
    """THE span sink (registered by phant_tpu/obs/__init__.py): roll a
    top-level `verify_block` record into the critpath family, update the
    coverage gauges, and capture an SLO exemplar when a budget blew."""
    if record.get("span") != "verify_block":
        return
    cfg = _cfg
    if not cfg.enabled:
        return
    breakdown, unattributed, wall = attribute(record)
    if wall <= 0.0:
        return
    for label, v in breakdown.items():
        metrics.observe_hist("critpath.phase_seconds", v / 1e3, phase=label)
    metrics.observe_hist("critpath.wall_seconds", wall / 1e3)
    metrics.observe_hist("critpath.unattributed_seconds", unattributed / 1e3)
    metrics.count("critpath.requests")
    global _tot_wall_s, _tot_attr_s
    with _tot_lock:
        _tot_wall_s += wall / 1e3
        # clipped tiling means attributed <= wall by construction; min()
        # keeps a malformed record from ever claiming > 100% coverage
        _tot_attr_s += min(wall - unattributed, wall) / 1e3
        cov = 100.0 * _tot_attr_s / _tot_wall_s if _tot_wall_s > 0 else 0.0
    metrics.gauge_set("critpath.coverage_pct", round(cov, 2))
    metrics.gauge_set("critpath.unattributed_pct", round(100.0 - cov, 2))
    # SLO exemplars: wall budget first (the headline trigger), then the
    # sampled near-budget tier, then the per-phase overrides — ONE
    # capture per request, first trigger wins
    if cfg.budget_ms > 0 and wall > cfg.budget_ms:
        _capture_slow(
            record, breakdown, wall, "wall", cfg.budget_ms, wall - cfg.budget_ms
        )
        return
    if (
        cfg.budget_ms > 0
        and cfg.near_pct > 0
        and wall > cfg.budget_ms * (1.0 - cfg.near_pct / 100.0)
    ):
        n = cfg.near_sample_n
        if n == 1 or (n > 1 and _near_rng.randrange(n) == 0):
            # over_ms is NEGATIVE here: the headroom this near-miss
            # still had under the budget
            _capture_slow(
                record,
                breakdown,
                wall,
                "near",
                cfg.budget_ms,
                wall - cfg.budget_ms,
            )
            return
    for label, limit in cfg.phase_budgets_ms.items():
        v = breakdown.get(label, 0.0)
        if v > limit:
            _capture_slow(record, breakdown, wall, label, limit, v - limit)
            return
