"""Unified timeline export (PR 16): tail-sampled Perfetto traces.

PR 15 built the instruments — critpath phase tiling, per-lane busy
gauges, `/debug/slow`, the on-demand profiler — but each is an island:
span records are ring entries, batch intervals are flight records, busy
windows are gauges, and the XLA profiler writes its own directory.
Nothing lines them up on ONE time axis. This module is that axis: an
always-on, bounded-memory timeline recorder — a third span sink plus
taps on the scheduler/mesh batch finishers and the BusyAccountant —
whose `export(window_s)` renders the recent past as Chrome-trace JSON
(the `traceEvents` object format) that Perfetto loads directly:

* pid 1 "requests"   — one track per HTTP handler thread; each kept
  request is a `verify_block` slice tiled with its critpath phase
  sub-slices (laid SEQUENTIALLY in pipeline order from the span's
  phase totals — a reconstruction, not measured start offsets);
* pid 2 "lanes"      — one track per (lane, device): witness/root/sig
  batch slices with prefetch/pack/dispatch/resolve sub-stages, keyed
  by batch_id;
* pid 3 "devices"    — per-device busy slices from the BusyAccountant's
  union-of-intervals open/close transitions;
* pid 4 "profiler"   — one slice + start/end instants per
  `POST /debug/profile` capture inside the window, so the XLA device
  trace can be laid alongside the host timeline (clock-sync metadata
  rides in `metadata.clock_sync`).

Flow events stitch a request to the merged batches that served it: the
request slice emits a `ph:"s"` per (lane, batch_id) it carries
(`batch_id` / `root_batch_id` / `sig_batch_id` span attrs), and the
batch slice answers with a `ph:"f", bp:"e"` — one arrow per kept
request, id `lane:batch_id:trace_id`. Pairing is guaranteed at export
time: a request only emits an `s` for a batch present in the window,
and a batch only emits `f`s for kept requests that reference it.

Full recording at 1000 blocks/s is unaffordable, so retention is
TAIL-SAMPLED at span close, in priority order:

  error    the request crashed (-32052 / any exception) — always kept
  slo      wall clock blew `--slo-budget-ms` (critpath's budget) — kept
  p99      the request is the rolling per-phase p99 exemplar (internal
           per-phase bucket counts; thresholds recached every 32
           requests once a phase has enough samples)
  sample   uniform 1-in-N (`--timeline-sample-n` / env), injectable RNG

and everything else drops with `reason=sampled_out`. Sampling is never
silent: `obs.timeline_kept{reason=}` + `obs.timeline_dropped{reason=
sampled_out}` reconcile EXACTLY with offered load (the bench section
asserts it), and a kept entry later evicted by ring overflow counts
`reason=ring_full` separately.

Config is resolved ONCE and memoized (`_Config`, exactly the critpath
pattern — the env-read-per-event anti-pattern the r14 signer fix
removed stays dead): `refresh_from_env()` re-reads (the Engine API
server calls it at construction, after the CLI wrote its flags into
the env), `configure()` overrides directly (tests, the bench A/B).
`PHANT_TIMELINE=0` disables the whole layer — the off leg of the
`timeline_overhead` bench section.

Thread-safety: one module lock guards the rings, the tail-sample
counters, and the p99 state; every tap is O(1) dict work under it.
The sink must never fail the traced work — span() swallows sink
exceptions, and the batch/busy taps are called outside scheduler locks.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from phant_tpu.obs import critpath
from phant_tpu.obs.flight import flight
from phant_tpu.utils.trace import DEFAULT_BUCKETS, histogram_quantile, metrics

#: keep-reason priority order (first match wins); the vocabulary of the
#: `obs.timeline_kept{reason=}` family
KEEP_REASONS: Tuple[str, ...] = ("error", "slo", "p99", "sample")

#: drop reasons: `sampled_out` at the span-close decision (reconciles
#: with offered load), `ring_full` when overflow evicts a KEPT entry
DROP_REASONS: Tuple[str, ...] = ("sampled_out", "ring_full")

#: recompute the per-phase p99 thresholds every this many sink calls —
#: a histogram_quantile over 15 buckets x 12 phases is cheap but not
#: per-request cheap
_P99_RECACHE_EVERY = 32

#: a phase needs this many samples before its p99 threshold is trusted
#: (an empty histogram's "p99" would keep everything)
_P99_MIN_COUNT = 64


class _Config:
    __slots__ = ("enabled", "sample_n", "ring", "dirpath", "keep")

    def __init__(
        self,
        enabled: bool,
        sample_n: int,
        ring: int,
        dirpath: str,
        keep: int,
    ):
        self.enabled = enabled
        self.sample_n = sample_n
        self.ring = ring
        self.dirpath = dirpath
        self.keep = keep


def _config_from_env() -> _Config:
    def _int(name: str, default: int, floor: int = 0) -> int:
        try:
            v = int(os.environ.get(name, str(default)) or str(default))
        except ValueError:
            return default
        return max(v, floor)

    return _Config(
        enabled=os.environ.get("PHANT_TIMELINE", "1") not in ("0", ""),
        sample_n=_int("PHANT_TIMELINE_SAMPLE_N", 16),
        ring=_int("PHANT_TIMELINE_RING", 1024, floor=1),
        dirpath=os.environ.get("PHANT_TIMELINE_DIR", ""),
        keep=_int("PHANT_TIMELINE_KEEP", 8, floor=1),
    )


_cfg: _Config = _config_from_env()
_lock = threading.Lock()

#: uniform 1-in-N sampler; tests/bench inject a seeded Random via
#: configure(rng=...) so the sample decision sequence is pinned
_rng = random.Random()

# the rings (all bounded by cfg.ring except profiles, which are rare):
# requests/batches carry the flow-joinable entries, busy the device
# occupancy slices, profiles the clock-sync markers
_requests: deque = deque(maxlen=_cfg.ring)
_batches: deque = deque(maxlen=_cfg.ring)
_busy: deque = deque(maxlen=_cfg.ring)
_profiles: deque = deque(maxlen=16)

# tail-sample accounting (mirrored to obs.timeline_{kept,dropped})
_kept: Dict[str, int] = {}
_dropped: Dict[str, int] = {}

# rolling per-phase p99 exemplar state: non-cumulative DEFAULT_BUCKETS
# counts (+Inf slot) per critpath phase, thresholds recached every
# _P99_RECACHE_EVERY sink calls
_phase_counts: Dict[str, List[int]] = {}
_p99_ms: Dict[str, float] = {}
_since_recache = 0

#: per-export spool suffix (same-second exports stay distinct)
_spool_seq = 0


def refresh_from_env() -> None:
    """Re-resolve the memoized config from the environment (the Engine
    API server calls this at construction so `--timeline-*` flags take
    effect; tests call it after monkeypatching). A ring-size change
    rebuilds the deques, keeping the newest entries."""
    global _cfg
    with _lock:
        _cfg = _config_from_env()
        _resize_locked(_cfg.ring)


def configure(
    enabled: Optional[bool] = None,
    sample_n: Optional[int] = None,
    ring: Optional[int] = None,
    dirpath: Optional[str] = None,
    keep: Optional[int] = None,
    rng: Optional[random.Random] = None,
) -> None:
    """Override the memoized config directly (tests, the bench A/B
    legs); None leaves a field as-is. `rng` replaces the uniform
    sampler's generator (determinism for tests)."""
    global _cfg, _rng
    with _lock:
        _cfg = _Config(
            enabled=_cfg.enabled if enabled is None else enabled,
            sample_n=_cfg.sample_n if sample_n is None else max(int(sample_n), 0),
            ring=_cfg.ring if ring is None else max(int(ring), 1),
            dirpath=_cfg.dirpath if dirpath is None else dirpath,
            keep=_cfg.keep if keep is None else max(int(keep), 1),
        )
        if rng is not None:
            _rng = rng
        _resize_locked(_cfg.ring)


def _resize_locked(n: int) -> None:
    global _requests, _batches, _busy
    if _requests.maxlen != n:
        _requests = deque(_requests, maxlen=n)
        _batches = deque(_batches, maxlen=n)
        _busy = deque(_busy, maxlen=n)


def enabled() -> bool:
    """Is the timeline recorder on? Read by the batch/busy taps before
    building their entry dicts."""
    return _cfg.enabled


def capacity() -> int:
    """The request-ring capacity (echoed by /healthz `debug_rings`)."""
    return _cfg.ring


def stats() -> Dict[str, Dict[str, int]]:
    """{'kept': {reason: n}, 'dropped': {reason: n}} since process start
    or the last reset() — the reconciliation surface: sum(kept.values())
    + dropped['sampled_out'] == offered requests (ring_full evictions
    count previously-KEPT entries, separately)."""
    with _lock:
        return {"kept": dict(_kept), "dropped": dict(_dropped)}


def reset() -> None:
    """Clear the rings, the tail-sample counters, and the p99 state
    (tests and the bench section start from a clean slate)."""
    global _since_recache
    with _lock:
        _requests.clear()
        _batches.clear()
        _busy.clear()
        _profiles.clear()
        _kept.clear()
        _dropped.clear()
        _phase_counts.clear()
        _p99_ms.clear()
        _since_recache = 0


# -- tail-sampled span sink (registered by phant_tpu/obs/__init__.py) --------


def _bucket_observe_locked(phase: str, v_ms: float) -> None:
    counts = _phase_counts.get(phase)
    if counts is None:
        counts = _phase_counts[phase] = [0] * (len(DEFAULT_BUCKETS) + 1)
    v_s = v_ms / 1e3
    for i, ub in enumerate(DEFAULT_BUCKETS):
        if v_s <= ub:
            counts[i] += 1
            return
    counts[-1] += 1


def _recache_p99_locked() -> None:
    for phase, counts in _phase_counts.items():
        if sum(counts) >= _P99_MIN_COUNT:
            _p99_ms[phase] = (
                histogram_quantile(DEFAULT_BUCKETS, counts, 0.99) * 1e3
            )


def _keep_reason_locked(
    record: dict, breakdown: Dict[str, float], wall_ms: float
) -> Optional[str]:
    if record.get("error"):
        return "error"
    budget = critpath.budget_ms()
    if budget > 0 and wall_ms > budget:
        return "slo"
    for phase, v in breakdown.items():
        thr = _p99_ms.get(phase, 0.0)
        if thr > 0.0 and v >= thr:
            return "p99"
    n = _cfg.sample_n
    if n == 1 or (n > 1 and _rng.randrange(n) == 0):
        return "sample"
    return None


def on_span(record: dict) -> None:
    """THE third span sink: tail-sample one top-level `verify_block`
    record into the request ring at span close."""
    if record.get("span") != "verify_block":
        return
    cfg = _cfg
    if not cfg.enabled:
        return
    end_wall = time.time()
    breakdown, _unattributed, wall = critpath.attribute(record)
    if wall <= 0.0:
        return
    flows: List[Tuple[str, int]] = []
    for lane, key in (
        ("witness", "batch_id"),
        ("root", "root_batch_id"),
        ("sig", "sig_batch_id"),
    ):
        bid = record.get(key)
        if isinstance(bid, int):
            flows.append((lane, bid))
    thread = threading.current_thread()
    with _lock:
        global _since_recache
        _since_recache += 1
        if _since_recache >= _P99_RECACHE_EVERY:
            _since_recache = 0
            _recache_p99_locked()
        reason = _keep_reason_locked(record, breakdown, wall)
        for phase, v in breakdown.items():
            _bucket_observe_locked(phase, v)
        evicted = False
        if reason is None:
            _dropped["sampled_out"] = _dropped.get("sampled_out", 0) + 1
        else:
            if len(_requests) == _requests.maxlen:
                # overflow evicts the OLDEST kept entry — counted so a
                # too-small ring can never silently eat the tail
                _dropped["ring_full"] = _dropped.get("ring_full", 0) + 1
                evicted = True
            _requests.append(
                {
                    "end": end_wall,
                    "dur_ms": wall,
                    "trace_id": record.get("trace_id"),
                    "tid": thread.ident,
                    "thread": thread.name,
                    "reason": reason,
                    "block": record.get("block"),
                    "error": record.get("error"),
                    "phases": {k: round(v, 3) for k, v in breakdown.items()},
                    "flows": flows,
                }
            )
            _kept[reason] = _kept.get(reason, 0) + 1
    if reason is None:
        metrics.count("obs.timeline_dropped", reason="sampled_out")
    else:
        metrics.count("obs.timeline_kept", reason=reason)
        if evicted:
            metrics.count("obs.timeline_dropped", reason="ring_full")


# -- batch / busy / profiler taps --------------------------------------------


def record_batch(
    record: dict,
    lane: str,
    duration_ms: float,
    trace_ids: Sequence[Optional[str]],
) -> None:
    """One finished lane batch (called by the scheduler's witness/plan
    finishers and, through them, every mesh lane + megabatch): the
    [picked, done] interval with its stage timings, keyed by batch_id —
    the `f` side of the request flow arrows."""
    if not _cfg.enabled:
        return
    entry = {
        "end": time.time(),
        "dur_ms": float(duration_ms),
        "lane": lane,
        "device": str(record.get("device", "0")),
        "batch_id": record.get("batch_id"),
        "batch_size": record.get("batch_size"),
        "backend": record.get("backend"),
        "bucket_bytes": record.get("bucket_bytes"),
        "trace_ids": [t for t in trace_ids if t],
    }
    for key in ("prefetch_ms", "pack_ms", "resolve_ms"):
        v = record.get(key)
        if isinstance(v, (int, float)) and v > 0:
            entry[key] = float(v)
    with _lock:
        _batches.append(entry)


def record_busy(device: str, start_wall: float, end_wall: float) -> None:
    """One closed device-busy interval (the BusyAccountant's open-count
    1->0 transition): a slice on the pid-3 device track."""
    if not _cfg.enabled or end_wall <= start_wall:
        return
    with _lock:
        _busy.append(
            {"device": str(device), "start": start_wall, "end": end_wall}
        )


def record_profile(path: str, start_wall: float, end_wall: float) -> None:
    """One on-demand profiler capture window (POST /debug/profile):
    start/end markers on the profiler track + `metadata.clock_sync`, so
    the XLA device trace under `path` can be laid alongside the host
    timeline."""
    if not _cfg.enabled:
        return
    with _lock:
        _profiles.append(
            {"path": path, "start": start_wall, "end": end_wall}
        )


# -- export ------------------------------------------------------------------

#: Chrome-trace process ids (one per track family); M metadata names them
_PID_REQUESTS = 1
_PID_LANES = 2
_PID_DEVICES = 3
_PID_PROFILER = 4


def _us(t: float) -> int:
    return int(t * 1e6)


def export(window_s: float) -> dict:
    """Render the last `window_s` seconds as a Chrome-trace JSON object
    (Perfetto-loadable `traceEvents` + metadata). Spools a rotated copy
    under the configured timeline dir when one is set."""
    now = time.time()
    cutoff = now - float(window_s)
    with _lock:
        reqs = [r for r in _requests if r["end"] >= cutoff]
        bats = [b for b in _batches if b["end"] >= cutoff]
        busy = [b for b in _busy if b["end"] >= cutoff]
        profs = [p for p in _profiles if p["end"] >= cutoff]
        kept = dict(_kept)
        dropped = dict(_dropped)
    events: List[dict] = []

    def meta(pid: int, name: str, tid: Optional[int] = None) -> None:
        ev = {
            "ph": "M",
            "pid": pid,
            "ts": 0,
            "name": "process_name" if tid is None else "thread_name",
            "args": {"name": name},
        }
        if tid is not None:
            ev["tid"] = tid
        else:
            ev["tid"] = 0
        events.append(ev)

    # -- requests (pid 1): one track per handler thread ----------------------
    if reqs:
        meta(_PID_REQUESTS, "requests")
    threads_named = set()
    batch_keys = {(b["lane"], b["batch_id"]) for b in bats}
    # (lane, batch_id) -> [(flow_id, s_ts_us)] for the batch-side `f`s
    flow_refs: Dict[Tuple[str, int], List[Tuple[str, int]]] = {}
    for r in reqs:
        tid = int(r["tid"] or 0)
        if tid not in threads_named:
            threads_named.add(tid)
            meta(_PID_REQUESTS, str(r["thread"]), tid=tid)
        start_us = _us(r["end"] - r["dur_ms"] / 1e3)
        dur_us = int(r["dur_ms"] * 1e3)
        events.append(
            {
                "ph": "X",
                "pid": _PID_REQUESTS,
                "tid": tid,
                "ts": start_us,
                "dur": max(dur_us, 1),
                "name": "verify_block",
                "cat": "request",
                "args": {
                    "trace_id": r["trace_id"],
                    "block": r["block"],
                    "reason": r["reason"],
                    "error": r["error"],
                },
            }
        )
        # phase sub-slices: SEQUENTIAL layout in pipeline order from the
        # span's phase totals — a reconstruction (the span measures
        # totals, not offsets), honest about being one
        off = start_us
        for phase in critpath.PHASES:
            v = r["phases"].get(phase)
            if not v:
                continue
            pdur = int(v * 1e3)
            events.append(
                {
                    "ph": "X",
                    "pid": _PID_REQUESTS,
                    "tid": tid,
                    "ts": off,
                    "dur": max(pdur, 1),
                    "name": phase,
                    "cat": "phase",
                    "args": {"ms": v},
                }
            )
            off += max(pdur, 1)
        for lane, bid in r["flows"]:
            if (lane, bid) not in batch_keys:
                continue  # the serving batch fell outside the window
            fid = f"{lane}:{bid}:{r['trace_id']}"
            s_ts = start_us + 1
            events.append(
                {
                    "ph": "s",
                    "pid": _PID_REQUESTS,
                    "tid": tid,
                    "ts": s_ts,
                    "name": "serves",
                    "cat": "batch_link",
                    "id": fid,
                }
            )
            flow_refs.setdefault((lane, bid), []).append((fid, s_ts))

    # -- lanes (pid 2): one track per (lane, device) -------------------------
    if bats:
        meta(_PID_LANES, "lanes")
    lane_tids: Dict[Tuple[str, str], int] = {}
    for key in sorted({(b["lane"], b["device"]) for b in bats}):
        lane_tids[key] = len(lane_tids) + 1
        meta(_PID_LANES, f"{key[0]} lane · dev {key[1]}", tid=lane_tids[key])
    for b in bats:
        tid = lane_tids[(b["lane"], b["device"])]
        start_us = _us(b["end"] - b["dur_ms"] / 1e3)
        dur_us = max(int(b["dur_ms"] * 1e3), 1)
        events.append(
            {
                "ph": "X",
                "pid": _PID_LANES,
                "tid": tid,
                "ts": start_us,
                "dur": dur_us,
                "name": f"{b['lane']} batch",
                "cat": "batch",
                "args": {
                    "batch_id": b["batch_id"],
                    "batch_size": b["batch_size"],
                    "backend": b["backend"],
                    "bucket_bytes": b["bucket_bytes"],
                    "requests": len(b["trace_ids"]),
                },
            }
        )
        # stage sub-slices: prefetch/pack at the start, resolve at the
        # end, dispatch = the remainder in between (clipped so stages
        # can never claim more than the batch interval)
        rem = dur_us
        off = start_us
        for stage in ("prefetch", "pack"):
            v = b.get(f"{stage}_ms")
            if not v:
                continue
            sdur = min(int(v * 1e3), rem)
            if sdur <= 0:
                continue
            events.append(
                {
                    "ph": "X",
                    "pid": _PID_LANES,
                    "tid": tid,
                    "ts": off,
                    "dur": sdur,
                    "name": stage,
                    "cat": "stage",
                    "args": {"ms": v},
                }
            )
            off += sdur
            rem -= sdur
        rdur = 0
        rv = b.get("resolve_ms")
        if rv:
            rdur = min(int(rv * 1e3), rem)
            if rdur > 0:
                events.append(
                    {
                        "ph": "X",
                        "pid": _PID_LANES,
                        "tid": tid,
                        "ts": start_us + dur_us - rdur,
                        "dur": rdur,
                        "name": "resolve",
                        "cat": "stage",
                        "args": {"ms": rv},
                    }
                )
                rem -= rdur
        if rem > 0:
            events.append(
                {
                    "ph": "X",
                    "pid": _PID_LANES,
                    "tid": tid,
                    "ts": off,
                    "dur": rem,
                    "name": "dispatch",
                    "cat": "stage",
                    "args": {},
                }
            )
        # the `f` side of the flow arrows: one per kept request this
        # batch served, bound to the enclosing batch slice (bp: "e"),
        # clamped after its `s` so begin/end always pair in order
        for fid, s_ts in flow_refs.get((b["lane"], b["batch_id"]), ()):
            events.append(
                {
                    "ph": "f",
                    "bp": "e",
                    "pid": _PID_LANES,
                    "tid": tid,
                    "ts": max(start_us + dur_us // 2, s_ts + 1),
                    "name": "serves",
                    "cat": "batch_link",
                    "id": fid,
                }
            )

    # -- devices (pid 3): busy slices ----------------------------------------
    if busy:
        meta(_PID_DEVICES, "devices")
    dev_tids: Dict[str, int] = {}
    for dev in sorted({b["device"] for b in busy}):
        dev_tids[dev] = len(dev_tids) + 1
        meta(_PID_DEVICES, f"device {dev}", tid=dev_tids[dev])
    for b in busy:
        events.append(
            {
                "ph": "X",
                "pid": _PID_DEVICES,
                "tid": dev_tids[b["device"]],
                "ts": _us(b["start"]),
                "dur": max(_us(b["end"]) - _us(b["start"]), 1),
                "name": "busy",
                "cat": "busy",
                "args": {},
            }
        )

    # -- profiler (pid 4): capture windows + clock-sync instants -------------
    clock_sync = []
    if profs:
        meta(_PID_PROFILER, "profiler")
        meta(_PID_PROFILER, "xla capture", tid=1)
    for p in profs:
        s_us, e_us = _us(p["start"]), _us(p["end"])
        events.append(
            {
                "ph": "X",
                "pid": _PID_PROFILER,
                "tid": 1,
                "ts": s_us,
                "dur": max(e_us - s_us, 1),
                "name": "xla_capture",
                "cat": "profile",
                "args": {"path": p["path"]},
            }
        )
        for name, ts in (("capture_start", s_us), ("capture_end", e_us)):
            events.append(
                {
                    "ph": "i",
                    "s": "t",
                    "pid": _PID_PROFILER,
                    "tid": 1,
                    "ts": ts,
                    "name": name,
                    "cat": "profile",
                    "args": {"path": p["path"]},
                }
            )
        clock_sync.append(
            {"path": p["path"], "start_us": s_us, "end_us": e_us}
        )

    payload = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "window_s": float(window_s),
            "exported_at": now,
            "kept": kept,
            "dropped": dropped,
            "requests": len(reqs),
            "batches": len(bats),
            "clock_sync": clock_sync,
        },
    }
    metrics.count("obs.timeline_exports")
    flight.record(
        "obs.timeline_export",
        window_s=float(window_s),
        events=len(events),
        requests=len(reqs),
        batches=len(bats),
    )
    _spool(payload)
    return payload


def _spool(payload: dict) -> Optional[str]:
    """Write one rotated export file under the configured timeline dir
    (no-op when unset); best-effort — a spool failure must never fail
    the GET that triggered the export."""
    cfg = _cfg
    if not cfg.dirpath:
        return None
    global _spool_seq
    with _lock:
        _spool_seq += 1
        n = _spool_seq
    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
    path = os.path.join(
        cfg.dirpath, f"timeline-{stamp}-{os.getpid()}-{n}.json"
    )
    try:
        os.makedirs(cfg.dirpath, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, default=str)
        os.replace(tmp, path)
        spooled = sorted(
            f for f in os.listdir(cfg.dirpath)
            if f.startswith("timeline-") and f.endswith(".json")
        )
        for stale in spooled[: -cfg.keep]:
            os.unlink(os.path.join(cfg.dirpath, stale))
    except OSError:
        return None
    return path
