"""Per-lane device-busy accounting (PR 15).

"How busy is each chip?" is the first question a real-hardware run asks,
and until now nothing answered it: the scheduler counts batches and the
watchdog flags stalls, but no gauge said "this lane's device computed 37%
of the last half minute". The two-phase begin/resolve protocol already
brackets device occupancy — begin_batch enqueues the device work with no
host sync, resolve_batch pays the readback — so each lane (the
single-executor scheduler, every MeshExecutorPool lane, and the root/sig
engine lanes riding the same executors) integrates the UNION of its
in-flight [begin, resolve] intervals here and exports it as
`sched.device_busy_pct{device=}`.

Union-of-intervals matters: with pipeline depth >= 2 a lane can hold two
dispatched batches at once, and summing their durations would read > 100%
busy. `BusyAccountant` keeps an open-interval count and accrues busy time
whenever it is nonzero — overlap cannot double-count, and gaps between
batches honestly read idle.

The window is ROLLING (two half-window buckets, default 30s each; the
carried bucket is capped at one window so a long eventless stretch can
never pin the gauge to a stale average): a gauge integrated since
process start would never move again after the first hour, while an
operator asking "is the chip idle at depth 1" wants the recent past.
Reads (`pct()` — both the /healthz surface and the /metrics scrape path
via VerificationScheduler.refresh_busy_gauges) advance the same
integration, so an idle lane decays toward 0 without traffic.

Honesty caveat (documented in README): the bracket covers
dispatch-enqueue through resolve-return, which includes the resolve
stage's host-side readback/commit work — on a real accelerator that is a
small tail; on the XLA-CPU proxy (whose "device" shares the host cores)
the gauge reads host+device occupancy of the lane, not chip utilization.

Timeline tap (PR 16): the union-of-intervals open count already marks
exactly when the device goes from idle to occupied (0 -> 1) and back
(1 -> 0) — each closed occupancy window is forwarded to the timeline
recorder (obs/timeline.py) as a busy slice on the device's track, wall-
clock stamped at the transition. The forward happens OUTSIDE our lock.

Thread-safety: one small lock per accountant; begin/end/pct are O(1)
arithmetic, cheap enough for the per-batch serving path. Gauge publishes
go through the metrics registry's own lock (never nested under ours).
`enabled=False` (the PHANT_OBS_ATTRIBUTION=0 switch, read at
scheduler/pool construction via obs.critpath.enabled()) makes every
method a no-op — the off leg of the obs_overhead bench A/B.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from phant_tpu.obs import timeline
from phant_tpu.utils.trace import metrics

#: default rolling half-window (seconds); two buckets => the gauge always
#: reflects the last 30..60s of lane activity
DEFAULT_WINDOW_S = 30.0


class BusyAccountant:
    """Union-of-intervals busy-time integrator for one device lane."""

    def __init__(
        self,
        device: str,
        window_s: float = DEFAULT_WINDOW_S,
        enabled: bool = True,
        publish: bool = True,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.device = str(device)
        self.enabled = enabled
        self._publish = publish
        self._window_s = max(window_s, 1e-3)
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        now = self._clock()
        self._open = 0  # in-flight [begin, resolve] intervals
        self._open_wall = 0.0  # wall clock of the last 0->1 transition
        self._last = now  # last integration timestamp
        self._win_start = now
        self._busy_cur = 0.0  # busy seconds in the current bucket
        self._busy_prev = 0.0  # busy seconds in the previous bucket
        self._prev_span = 0.0  # previous bucket's width (0 until one closes)
        if enabled and publish:
            # publish 0.0 at construction so every lane is PRESENT in
            # /metrics from boot — an operator must be able to tell "lane
            # 3 is idle" from "lane 3 never reported"
            metrics.gauge_set("sched.device_busy_pct", 0.0, device=self.device)

    # -- integration ---------------------------------------------------------

    def _advance_locked(self, now: float) -> None:
        dt = now - self._last
        if dt > 0:
            if self._open > 0:
                self._busy_cur += dt
            self._last = now
        span = now - self._win_start
        if span >= self._window_s:
            # the carried bucket is CAPPED at one window (busy scaled
            # proportionally): after a long idle or eventless stretch the
            # elapsed bucket can span minutes, and carrying it whole
            # would pin the gauge near the stale average for a full
            # window — the contract is "the last 30..60s", not "since
            # the last event"
            carry = min(span, self._window_s)
            self._busy_prev = self._busy_cur * (carry / span)
            self._prev_span = carry
            self._busy_cur = 0.0
            self._win_start = now

    def begin(self) -> None:
        """A batch's device work was enqueued (begin_batch returned)."""
        if not self.enabled:
            return
        with self._lock:
            self._advance_locked(self._clock())
            if self._open == 0:
                # idle -> occupied: the timeline busy slice opens here
                self._open_wall = time.time()
            self._open += 1

    def end(self) -> None:
        """A batch resolved (or its handle was abandoned on a crash path —
        the interval closes either way; extra end() calls clamp at 0)."""
        if not self.enabled:
            return
        closed = None
        with self._lock:
            self._advance_locked(self._clock())
            if self._open > 0:
                self._open -= 1
                if self._open == 0 and self._open_wall > 0.0:
                    # occupied -> idle: one closed union interval
                    closed = (self._open_wall, time.time())
            pct = self._pct_locked()
        if self._publish:
            metrics.gauge_set("sched.device_busy_pct", pct, device=self.device)
        if closed is not None and timeline.enabled():
            timeline.record_busy(self.device, closed[0], closed[1])

    def _pct_locked(self) -> float:
        span = self._prev_span + (self._last - self._win_start)
        if span <= 0:
            return 0.0
        busy = self._busy_prev + self._busy_cur
        return round(min(100.0, 100.0 * busy / span), 2)

    def pct(self) -> float:
        """The rolling busy percentage, integrated to NOW (reads advance
        the window, so an idle lane decays without traffic); republishes
        the gauge so /metrics and /healthz agree."""
        if not self.enabled:
            return 0.0
        with self._lock:
            self._advance_locked(self._clock())
            pct = self._pct_locked()
        if self._publish:
            metrics.gauge_set("sched.device_busy_pct", pct, device=self.device)
        return pct
