"""Request-scoped tracing and postmortem layer (on top of utils/trace.py).

PR 1 gave the process metrics and per-block spans; PR 3 gave the
continuous-batching scheduler. What was still missing is REQUEST identity
across the scheduler boundary — nothing tied an
`engine_executeStatelessPayloadV1` call to the queue wait, bucket, batch,
and device dispatch that served it — and any postmortem when the process
died. This package is that layer:

* **Trace context** (`utils/trace.py trace_context`): the Engine API
  server opens one per POST; the span a request opens and the scheduler
  jobs it submits all carry the request's `trace_id`. The scheduler
  attaches a batch record (`batch_id`, `queue_wait_ms`, `bucket_bytes`,
  `batch_size`, `backend`, cache hit/miss counts) to every job it
  executes, and `stateless.verify_witness_nodes` folds it into the
  request's top-level span — concurrent requests coalesced into one batch
  each get their own span linked by the shared `batch_id`.
* **Flight recorder** (`flight.py`): a bounded thread-safe ring of span /
  error / scheduler-transition records, served live at `GET /debug/flight`
  and dumped to `build/flight/` on executor crash, on `/healthz` flipping
  to 503, and on SIGTERM.
* **Watchdog** (`watchdog.py`): detects the executor stalling inside a
  batch (deadline overrun without a crash) and records it as a metric +
  flight event.
* **Critical-path attribution** (`critpath.py`, PR 15): a second span
  sink tiles every `verify_block` request's wall clock into the
  `critpath.*` phase family (queue wait / prefetch / pack / dispatch /
  resolve / sig_wait / EVM / post-root ...), gauges the unattributed
  residual (the honesty check), and captures SLO-busting requests as
  full span trees into a dedicated ring (`GET /debug/slow`).
* **Device-busy accounting** (`busy.py`, PR 15): per-lane
  union-of-intervals busy integration over the two-phase begin/resolve
  brackets — `sched.device_busy_pct{device=}` in /metrics and /healthz.
* **On-demand profiler** (`profiler.py`, PR 15): `POST /debug/profile`
  grabs a single-flight-guarded, hard-capped `jax_profile` window from a
  live server.
* **Timeline export** (`timeline.py`, PR 16): a third span sink plus
  batch/busy/profiler taps tail-sample the serving path into a bounded
  recorder, rendered as Perfetto-loadable Chrome-trace JSON at
  `GET /debug/timeline?window=S` — requests, lane batches, and device
  busy windows on one time axis, stitched by flow events.

Importing this package registers the flight recorder, the critpath
rollup, and the timeline recorder as span sinks, so any module that
touches obs gets span mirroring, attribution, and timeline capture for
free; the registrations are idempotent.
"""

from __future__ import annotations

from phant_tpu.obs import critpath, timeline
from phant_tpu.obs.busy import BusyAccountant
from phant_tpu.obs.flight import FlightRecorder, flight
from phant_tpu.obs.watchdog import Watchdog
from phant_tpu.utils.trace import add_span_sink

__all__ = [
    "BusyAccountant",
    "FlightRecorder",
    "Watchdog",
    "critpath",
    "flight",
    "record_span",
    "timeline",
]


def record_span(record: dict) -> None:
    """The span sink: mirror every top-level span record into the ring."""
    flight.record("span", span=record)


add_span_sink(record_span)
add_span_sink(critpath.rollup)
add_span_sink(timeline.on_span)
