"""Request-scoped tracing and postmortem layer (on top of utils/trace.py).

PR 1 gave the process metrics and per-block spans; PR 3 gave the
continuous-batching scheduler. What was still missing is REQUEST identity
across the scheduler boundary — nothing tied an
`engine_executeStatelessPayloadV1` call to the queue wait, bucket, batch,
and device dispatch that served it — and any postmortem when the process
died. This package is that layer:

* **Trace context** (`utils/trace.py trace_context`): the Engine API
  server opens one per POST; the span a request opens and the scheduler
  jobs it submits all carry the request's `trace_id`. The scheduler
  attaches a batch record (`batch_id`, `queue_wait_ms`, `bucket_bytes`,
  `batch_size`, `backend`, cache hit/miss counts) to every job it
  executes, and `stateless.verify_witness_nodes` folds it into the
  request's top-level span — concurrent requests coalesced into one batch
  each get their own span linked by the shared `batch_id`.
* **Flight recorder** (`flight.py`): a bounded thread-safe ring of span /
  error / scheduler-transition records, served live at `GET /debug/flight`
  and dumped to `build/flight/` on executor crash, on `/healthz` flipping
  to 503, and on SIGTERM.
* **Watchdog** (`watchdog.py`): detects the executor stalling inside a
  batch (deadline overrun without a crash) and records it as a metric +
  flight event.

Importing this package registers the flight recorder as a span sink, so
any module that touches obs gets span mirroring for free; the registration
is idempotent.
"""

from __future__ import annotations

from phant_tpu.obs.flight import FlightRecorder, flight
from phant_tpu.obs.watchdog import Watchdog
from phant_tpu.utils.trace import add_span_sink

__all__ = ["FlightRecorder", "Watchdog", "flight", "record_span"]


def record_span(record: dict) -> None:
    """The span sink: mirror every top-level span record into the ring."""
    flight.record("span", span=record)


add_span_sink(record_span)
