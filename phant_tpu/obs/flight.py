"""Flight recorder: a bounded ring of recent observability records.

A dead server must leave a postmortem without log scraping (BENCH_r05:
the driver killed the process and the round produced NO artifact at all —
the flight recorder is the serving-side answer to the same failure mode).
The ring holds the most recent span records, error records, and scheduler
state transitions (admit / shed / batch-start / batch-done / crash /
stall), each stamped with a wall clock, a monotonic sequence number, and —
when recorded inside a `trace_context` — the request's `trace_id`.

Three surfaces:

* `GET /debug/flight` (engine_api/server.py) serves the live ring as JSON;
* `dump(reason)` writes the ring to `build/flight/` as one JSON file —
  triggered on executor crash (serving/scheduler.py `_die`), on `/healthz`
  flipping to 503, and on SIGTERM (phant_tpu/__main__.py), and counted in
  `flight.dumps{reason=...}`; retention keeps the newest
  `PHANT_FLIGHT_KEEP` (default 16) dump files;
* tests/tools read `records()` directly.

Record kinds are vocabulary-gated: every `kind` passed to `record()` must
be a literal with a `trace.SPAN_HELP` entry (phantlint SPANNAME), exactly
as metric names are gated by METRIC_HELP.

Thread-safety: one lock guards the deque and the sequence counter; a
record is one dict build + append under it, cheap enough for the admission
path. `dump()` snapshots under the lock and writes outside it.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import List, Optional

from phant_tpu.utils.trace import current_trace_id, metrics

#: default ring capacity (records); override with --flight-ring /
#: PHANT_FLIGHT_RING (PHANT_FLIGHT_CAPACITY kept as the legacy alias)
_DEFAULT_CAPACITY = 2048


def _capacity_from_env() -> int:
    """Resolve the global ring's capacity ONCE (module import and
    `refresh_from_env()` — never per record): PHANT_FLIGHT_RING wins,
    the pre-PR-16 PHANT_FLIGHT_CAPACITY spelling still works."""
    raw = os.environ.get(
        "PHANT_FLIGHT_RING",
        os.environ.get("PHANT_FLIGHT_CAPACITY", str(_DEFAULT_CAPACITY)),
    )
    try:
        v = int(raw or str(_DEFAULT_CAPACITY))
    except ValueError:
        return _DEFAULT_CAPACITY
    return max(v, 1)


def _flight_dir() -> str:
    d = os.environ.get("PHANT_FLIGHT_DIR")
    if d:
        return d
    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, "build", "flight")


class FlightRecorder:
    """Bounded, thread-safe ring of observability records."""

    def __init__(self, capacity: int = _DEFAULT_CAPACITY):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._seq = 0
        self._dump_seq = 0

    def record(self, kind: str, **fields) -> None:
        """Append one record. `kind` must be a SPAN_HELP-cataloged literal
        (phantlint SPANNAME). A `trace_id` is attached automatically when
        the calling thread is inside a `trace_context` (explicit
        `trace_id=` wins)."""
        if "trace_id" not in fields:
            tid = current_trace_id()
            if tid is not None:
                fields["trace_id"] = tid
        rec = {"kind": kind, "t": time.time(), **fields}
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)

    def records(self) -> List[dict]:
        """Snapshot of the ring, oldest first."""
        with self._lock:
            return list(self._ring)

    def ring_capacity(self) -> int:
        """The capacity, read under the ring lock: handler threads report
        it (/healthz debug_rings) while resize()/refresh_from_env()
        rewrite it — the unlocked attribute read was phantsan's first
        real-tree catch (the same field the pre-PR-16 dump() bug tore)."""
        with self._lock:
            return self.capacity

    def snapshot(self) -> dict:
        """Capacity AND records from one lock region — a /debug/flight
        reply must not pair a post-resize capacity with a pre-resize
        ring."""
        with self._lock:
            return {"capacity": self.capacity, "records": list(self._ring)}

    def resize(self, capacity: int) -> None:
        """Rebuild the ring at a new capacity, keeping the NEWEST records
        (a shrink drops from the oldest end — ring semantics)."""
        capacity = max(int(capacity), 1)
        with self._lock:
            if self._ring.maxlen != capacity:
                self._ring = deque(self._ring, maxlen=capacity)
            self.capacity = capacity

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- postmortem dumps ----------------------------------------------------

    def dump(self, reason: str, dirpath: Optional[str] = None) -> Optional[str]:
        """Write the ring to `<dir>/flight-<utc>-<reason>-<pid>.json` and
        return the path (None when the write itself fails — a postmortem
        path must never take the process down with it). Prunes the dump dir
        to the newest PHANT_FLIGHT_KEEP files."""
        d = dirpath or _flight_dir()
        snap = self.records()
        with self._lock:
            self._dump_seq += 1
            dump_n = self._dump_seq  # same-second same-reason dumps stay distinct
            cap = self.capacity  # resize() mutates under the same lock
        payload = {
            "reason": reason,
            "dumped_at": time.time(),
            "pid": os.getpid(),
            "capacity": cap,
            "records": snap,
        }
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        path = os.path.join(
            d, f"flight-{stamp}-{reason}-{os.getpid()}-{dump_n}.json"
        )
        try:
            os.makedirs(d, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, default=str)
            os.replace(tmp, path)
        except OSError:
            return None
        metrics.count("flight.dumps", reason=reason)
        self.record("flight.dump", reason=reason, path=path, n_records=len(snap))
        self._prune(d)
        return path

    @staticmethod
    def _prune(d: str) -> None:
        keep = int(os.environ.get("PHANT_FLIGHT_KEEP", "16"))
        try:
            dumps = sorted(
                f for f in os.listdir(d)
                if f.startswith("flight-") and f.endswith(".json")
            )
            for stale in dumps[:-keep] if keep > 0 else []:
                os.unlink(os.path.join(d, stale))
        except OSError:
            pass  # retention is best-effort; the fresh dump already landed


#: process-global recorder (importable singleton, like trace.metrics)
flight = FlightRecorder(capacity=_capacity_from_env())


def refresh_from_env() -> None:
    """Re-resolve the global ring's capacity from the environment (the
    Engine API server calls this at construction, after the CLI wrote
    `--flight-ring` into the env — the once-at-construction contract,
    NOT re-read per record)."""
    flight.resize(_capacity_from_env())
