"""Executor stall watchdog.

An executor that CRASHES is already loud (scheduler `_die`: futures fail
fast, `/healthz` 503, flight dump). An executor that STALLS — wedged
inside a device call that never returns, the exact r3/r5 tunnel failure
mode — is silent: the queue grows, requests time out one by one, and
nothing says why. The watchdog closes that gap: a daemon thread polls the
scheduler's in-flight state and, when the batch being executed has
out-lived its deadline, records the stall ONCE per batch as

* `sched.watchdog_stalls` (counter) and
* a `sched.stall` flight event carrying the batch id, lane, overdue time,
  and the trace ids of every coalesced request —

so a postmortem dump of a wedged server names the batch that wedged it.
The scheduler starts one per instance (serving/scheduler.py) and stops it
on shutdown/death; detection is passive (the watchdog never kills or
requeues — policy stays with the operator/orchestrator).
"""

from __future__ import annotations

import threading
from typing import Optional

from phant_tpu.obs.flight import flight
from phant_tpu.utils.trace import metrics

#: default poll interval (seconds); a stall is a seconds-scale condition
_DEFAULT_INTERVAL_S = 0.25


# the one mutable field, _last_flagged, is read and written ONLY by the
# watchdog's own worker thread (_run); start/stop touch the Event, which
# carries its own lock
class Watchdog:  # phantlint: disable=THREADSHARE — worker-thread-private state
    """Polls `source()` — a callable returning the in-flight descriptor
    `{"batch_id", "lane", "started", "deadline", "trace_ids"}` or None —
    and records each batch's first deadline overrun."""

    def __init__(self, source, interval_s: float = _DEFAULT_INTERVAL_S):
        self._source = source
        self._interval_s = interval_s
        self._stop = threading.Event()
        self._last_flagged: Optional[int] = None  # batch_id, once per batch
        self._thread = threading.Thread(
            target=self._run, name="phant-obs-watchdog", daemon=True
        )

    def start(self) -> "Watchdog":
        self._thread.start()
        return self

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout)

    def _run(self) -> None:
        import time

        while not self._stop.wait(self._interval_s):
            try:
                st = self._source()
            except Exception:
                continue  # a racing shutdown must not kill the watchdog
            if st is None or st.get("deadline") is None:
                continue
            now = time.monotonic()
            if now <= st["deadline"] or st.get("batch_id") == self._last_flagged:
                continue
            self._last_flagged = st.get("batch_id")
            overdue_ms = round((now - st["deadline"]) * 1e3, 1)
            metrics.count("sched.watchdog_stalls")
            flight.record(
                "sched.stall",
                batch_id=st.get("batch_id"),
                lane=st.get("lane"),
                # which pipeline stage the wedged batch was in (pack/
                # dispatch/resolve — serving/scheduler.py descriptors),
                # and which mesh device lane was running it (None on the
                # single-executor path): a wedged chip gets NAMED
                stage=st.get("stage"),
                device=st.get("device"),
                inflight_ms=round((now - st["started"]) * 1e3, 1),
                overdue_ms=overdue_ms,
                trace_ids=st.get("trace_ids"),
            )
