"""On-demand TPU profiler capture (PR 15): POST /debug/profile.

`jax_profile` (utils/trace.py) existed since PR 1 — but only as a
context manager reachable from bench.py and the `--trace-logdir` flag,
i.e. you had to DECIDE to profile before starting the server. A real-v5e
load run wants the opposite: the server is mid-traffic, a latency gauge
looks wrong, grab an XLA trace of the NEXT T seconds without restarting.
`capture(seconds)` is that: it wraps `jax.profiler.start/stop_trace`
around a sleep on the calling (HTTP handler) thread while the serving
threads keep working — the profiler records the whole process, so the
capture window sees every lane's dispatches.

Guards, because this is a debug surface on a serving box:

* SINGLE-FLIGHT — jax supports one active trace per process; a second
  capture attempt raises `ProfileBusy` (the server maps it to HTTP 503)
  instead of corrupting the first.
* HARD CAP — the window is clamped to PHANT_PROFILE_MAX_S (default 30):
  a fat-fingered `seconds=3600` must not pin a handler thread (and the
  profiler's memory growth) for an hour.
* The trace directory defaults to `build/profile/` and is overridden by
  `--profile-dir` / PHANT_PROFILE_DIR; each capture gets its own
  timestamped subdirectory so repeated grabs never overwrite.

Every capture leaves an `obs.profile` flight record (directory, window,
artifact count) so the postmortem ring knows a profiler ran — a capture
perturbs the very latencies it measures, and the audit trail keeps that
honest. View artifacts with TensorBoard or Perfetto (xplane/trace.json).
"""

from __future__ import annotations

import math
import os
import threading
import time

from phant_tpu.obs.flight import flight

#: default hard cap on one capture window (seconds)
_DEFAULT_MAX_S = 30.0


class ProfileBusy(Exception):
    """A capture is already in flight (jax allows one trace per process)."""


class ProfileError(Exception):
    """The profiler itself failed (jax absent, trace dir unwritable, ...)."""


_inflight = threading.Lock()
#: per-capture suffix; only ever touched under the _inflight guard
_seq = 0


def profile_dir() -> str:
    d = os.environ.get("PHANT_PROFILE_DIR")
    if d:
        return d
    root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    return os.path.join(root, "build", "profile")


def max_seconds() -> float:
    try:
        v = float(os.environ.get("PHANT_PROFILE_MAX_S", str(_DEFAULT_MAX_S)))
    except ValueError:
        return _DEFAULT_MAX_S
    return v if v > 0 else _DEFAULT_MAX_S


def capture(seconds: float) -> dict:
    """Run one profiler capture of `seconds` (clamped to the hard cap);
    returns {"path", "seconds", "artifacts"}. Raises ValueError on a
    non-positive/non-finite window, ProfileBusy on overlap, ProfileError
    when the profiler fails. Blocks the CALLING thread for the window —
    the HTTP handler thread, by design: the reply lands when the
    artifacts are on disk."""
    s = float(seconds)
    if not math.isfinite(s) or s <= 0:
        raise ValueError(f"profile window must be a positive number, got {seconds!r}")
    s = min(s, max_seconds())
    if not _inflight.acquire(blocking=False):
        raise ProfileBusy("a profiler capture is already in flight")
    try:
        global _seq
        _seq += 1
        n = _seq
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        path = os.path.join(
            profile_dir(), f"profile-{stamp}-{os.getpid()}-{n}"
        )
        t_start = time.time()
        try:
            os.makedirs(path, exist_ok=True)
            from phant_tpu.utils.trace import jax_profile

            with jax_profile(path):
                time.sleep(s)
        except Exception as e:
            raise ProfileError(f"profiler capture failed: {e!r}") from e
        t_end = time.time()
        artifacts = sum(len(files) for _d, _sub, files in os.walk(path))
        flight.record("obs.profile", path=path, seconds=s, artifacts=artifacts)
        # clock-sync marker: the capture window lands on the timeline's
        # profiler track so the XLA device trace can be laid alongside
        from phant_tpu.obs import timeline

        timeline.record_profile(path, t_start, t_end)
        return {"path": path, "seconds": s, "artifacts": artifacts}
    finally:
        _inflight.release()
