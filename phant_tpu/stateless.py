"""Stateless block execution from a witness.

The capability behind `engine_executeStatelessPayloadV1`: execute a block
against ONLY a multiproof witness (RLP trie nodes + contract codes), with no
resident world state, and recompute the post-state root over the witnessed
subtree. The reference client has the Engine API method in its supported
list but no implementation (reference: src/main.zig:24-54 lists it,
main.zig:58-70 implements only newPayloadV2) and skips state roots entirely
(reference: src/blockchain/blockchain.zig:83-85); this module is the north
star's actual product path — witness verification is the TPU-batched hot
loop (phant_tpu/ops/witness_jax.py), and execution runs over a lazily
materialized witness-backed StateDB.

Pieces:
- `PartialTrie`: an MPT reconstructed from witness nodes where unwitnessed
  subtrees are opaque `HashNode`s contributing their digest directly. Reads
  and writes that stay inside the witnessed region work; touching an
  unwitnessed subtree raises StatelessError (the witness is insufficient).
- `WitnessStateDB`: a StateDB that materializes accounts/storage on first
  access by walking the partial trie (account key = keccak(address), slot
  key = keccak(slot_be32)), and whose `state_root()` recomputes the post
  root by writing every dirty account back into the partial trie.

Deletion is fully supported: EIP-158 cleanup of touched-empty accounts,
selfdestruct, and storage-zeroing delete keys from the partial trie with
full branch-collapse/extension-merge re-normalization (phant_tpu/mpt/mpt.py
_delete). The one witness-shaped limit is inherent to stateless execution:
collapsing a branch down to a single unwitnessed (HashNode) sibling needs
that sibling's encoding, so such a witness raises StatelessError — witness
formats must include deletion siblings, as real stateless protocols do.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from phant_tpu import rlp
from phant_tpu.crypto.keccak import keccak256
from phant_tpu.mpt.mpt import (
    BranchNode,
    EMPTY_TRIE_ROOT,
    ExtensionNode,
    LeafNode,
    Trie,
    decode_hex_prefix,
)
from phant_tpu.state.statedb import StateDB
from phant_tpu.types.account import Account, EMPTY_CODE_HASH


class StatelessError(ValueError):
    """The witness is insufficient or unsupported for this execution."""


@dataclass
class HashNode:
    """An unwitnessed subtree: only its digest is known."""

    digest: bytes


def _decode_node(item: rlp.RLPItem, db: Dict[bytes, bytes]):
    """Decoded witness structure -> node graph (HashNode at witness edges)."""
    if not isinstance(item, list):
        raise StatelessError("trie node is not an RLP list")
    if len(item) == 17:
        branch = BranchNode()
        for i in range(16):
            child = item[i]
            if isinstance(child, list):
                branch.children[i] = _decode_node(child, db)
            elif len(child) == 0:
                branch.children[i] = None
            elif len(child) == 32:
                branch.children[i] = _resolve(bytes(child), db)
            else:
                raise StatelessError("bad branch child reference")
        value = bytes(item[16])
        branch.value = value if value else None
        return branch
    if len(item) == 2:
        path, is_leaf = decode_hex_prefix(bytes(item[0]))
        if is_leaf:
            return LeafNode(path, bytes(item[1]))
        child = item[1]
        if isinstance(child, list):
            return ExtensionNode(path, _decode_node(child, db))
        if len(child) == 32:
            return ExtensionNode(path, _resolve(bytes(child), db))
        raise StatelessError("bad extension child reference")
    raise StatelessError(f"trie node with {len(item)} items")


def _resolve(digest: bytes, db: Dict[bytes, bytes]):
    enc = db.get(digest)
    if enc is None:
        return HashNode(digest)
    return _decode_node(rlp.decode(enc), db)


class PartialTrie(Trie):
    """A trie over witness nodes; unwitnessed subtrees are HashNodes.

    Hashing: `root_hash()` is the host walk. Whether a partial trie's
    post-root re-hash runs here or as part of a batched device plan is
    decided by THE offload-gate story in ops/root_engine.py (single
    source of truth) — one witness subtree alone is below the
    device-dispatch break-even, but the serving path coalesces many
    requests' plans into one dispatch, which is where the device wins
    (WitnessStateDB.post_root_plan / compute_post_root)."""

    #: digest -> decoded node graph (scheme hook: the hexary witness
    #: decoder here; the binary scheme swaps in its strict 2-ary decoder,
    #: phant_tpu/commitment/binary.py)
    _resolve_witness = staticmethod(_resolve)

    def __init__(self, root_digest: bytes, db: Dict[bytes, bytes]):
        Trie.__init__(self)
        if root_digest != EMPTY_TRIE_ROOT:
            node = self._resolve_witness(root_digest, db)
            if isinstance(node, HashNode):
                raise StatelessError("witness is missing the root node")
            self.root = node
            self.approx_size = len(db)

    # --- reads ------------------------------------------------------------

    def get(self, key: bytes) -> Optional[bytes]:
        node, path = self.root, self._digits(key)
        while node is not None:
            if isinstance(node, HashNode):
                raise StatelessError(
                    f"witness does not cover key {key.hex()}"
                )
            if isinstance(node, LeafNode):
                return node.value if node.path == tuple(path) else None
            if isinstance(node, ExtensionNode):
                n = len(node.path)
                if tuple(path[:n]) != node.path:
                    return None
                node, path = node.child, path[n:]
                continue
            if not path:
                return node.value
            node, path = node.children[path[0]], path[1:]
        return None

    # --- writes -----------------------------------------------------------

    def put(self, key: bytes, value: bytes) -> None:
        if not value:  # empty value = delete (geth trie semantics)
            self.delete(key)
            return
        self._enc_cache.clear()
        self.root = _insert_partial(self.root, self._digits(key), value)

    def delete(self, key: bytes) -> None:
        """Remove `key` with full node collapse. Raises StatelessError when
        the collapse needs the structure of an unwitnessed sibling (a branch
        left with one HashNode child must merge the nibble into it, which
        requires its encoding) — the witness is insufficient, exactly the
        case stateless witness formats require sibling nodes for."""
        from phant_tpu.mpt.mpt import _delete, _Unresolved

        self._enc_cache.clear()
        try:
            self.root = _delete(self.root, self._digits(key))
        except _Unresolved:
            # _delete mutates in place on the way down, so the trie is now
            # half-deleted (key gone, collapse pending) — poison it so no
            # caller can hash the non-canonical structure
            self._broken = True
            raise StatelessError(
                "deletion collapse crosses an unwitnessed subtree "
                f"(key {key.hex()}); the witness must include sibling nodes"
            ) from None

    # --- hashing ----------------------------------------------------------

    def _ref(self, node):
        if isinstance(node, HashNode):
            return node.digest
        return super()._ref(node)

    def node_encoding(self, node):
        if isinstance(node, HashNode):
            raise StatelessError("cannot encode an unwitnessed subtree")
        return super().node_encoding(node)

    _broken = False  # set by a failed delete(); the structure is no longer
    # canonical and must never be hashed

    def root_hash(self) -> bytes:
        if self._broken:
            raise StatelessError(
                "partial trie is poisoned by a failed deletion collapse"
            )
        if isinstance(self.root, HashNode):
            return self.root.digest
        return super().root_hash()


def _insert_partial(node, path, value: bytes):
    """mpt._insert with HashNode awareness: descending INTO an unwitnessed
    subtree is an error; splitting an edge NEXT TO one is fine (the HashNode
    keeps contributing its digest from its new position)."""
    from phant_tpu.mpt.mpt import _common_prefix_len

    if node is None:
        return LeafNode(tuple(path), value)
    if isinstance(node, HashNode):
        raise StatelessError("write path crosses an unwitnessed subtree")

    if isinstance(node, LeafNode):
        if node.path == tuple(path):
            node.value = value
            return node
        common = _common_prefix_len(node.path, path)
        branch = BranchNode()
        old_rest, new_rest = node.path[common:], tuple(path[common:])
        if not old_rest:
            branch.value = node.value
        else:
            branch.children[old_rest[0]] = LeafNode(old_rest[1:], node.value)
        if not new_rest:
            branch.value = value
        else:
            branch.children[new_rest[0]] = LeafNode(new_rest[1:], value)
        if common:
            return ExtensionNode(tuple(path[:common]), branch)
        return branch

    if isinstance(node, ExtensionNode):
        common = _common_prefix_len(node.path, path)
        if common == len(node.path):
            node.child = _insert_partial(node.child, path[common:], value)
            return node
        branch = BranchNode()
        ext_rest = node.path[common:]
        if len(ext_rest) == 1:
            branch.children[ext_rest[0]] = node.child
        else:
            branch.children[ext_rest[0]] = ExtensionNode(ext_rest[1:], node.child)
        new_rest = tuple(path[common:])
        if not new_rest:
            branch.value = value
        else:
            branch.children[new_rest[0]] = LeafNode(new_rest[1:], value)
        if common:
            return ExtensionNode(tuple(path[:common]), branch)
        return branch

    # BranchNode
    if not path:
        node.value = value
        return node
    node.children[path[0]] = _insert_partial(node.children[path[0]], path[1:], value)
    return node


# ---------------------------------------------------------------------------
# witness-backed state
# ---------------------------------------------------------------------------


def witness_node_db(nodes: List[bytes]) -> Dict[bytes, bytes]:
    """The digest -> node-bytes map of one witness, built with ONE batched
    C keccak call instead of a per-node scalar loop — the request path's
    one and only witness decode (`stateless.witness_nodes_decoded` counts
    it, so a reintroduced second decode shows up as a doubled counter in
    the phase metrics). The witness-VERIFICATION decode lives elsewhere
    and is amortized: the serving prefetch stage pre-scans batches
    against the engine's intern tables (ops/witness_engine.py
    prefetch_batch), where the steady-state marginal cost per block is
    ~zero (cross-block reuse, PAPERS.md 2408.14217)."""
    from phant_tpu.crypto.keccak import keccak256_batch_cpu
    from phant_tpu.utils.trace import metrics

    metrics.count("stateless.witness_nodes_decoded", len(nodes))
    return dict(zip(keccak256_batch_cpu(nodes), nodes))


#: `_applied_accounts` sentinels: the account's leaf was deleted from the
#: trie / the address was never written under the current generation
_DELETED = object()
_UNSET = object()


class _RootPatch:
    """One account leaf awaiting its plan-computed storage root: the leaf
    was put with a zeroed 32-byte placeholder, and `apply_post_root`
    patches the real digest in once the plan resolves."""

    __slots__ = ("addr", "leaf", "prefix", "suffix", "gi", "fields")

    def __init__(self, addr, leaf, prefix, suffix, gi, fields):
        self.addr = addr
        self.leaf = leaf  # the LeafNode object inside the account trie
        self.prefix = prefix  # account-RLP bytes before the storage root
        self.suffix = suffix  # account-RLP bytes after it
        self.gi = gi  # the storage root's entry in the plan builder
        self.fields = fields  # (nonce, balance, code_hash)


class PostRootPlan:
    """A request's fused account+storage hash plan plus the host-side
    patch list (`WitnessStateDB.post_root_plan` -> serving root lane ->
    `apply_post_root`). `plan.out_rows` reads back one storage root per
    patch (same order) and the account root LAST."""

    __slots__ = ("plan", "patches")

    def __init__(self, plan, patches):
        self.plan = plan  # ops/mpt_jax.HashPlan
        self.patches = patches  # List[_RootPatch]

    @property
    def levels(self) -> int:
        return len(self.plan.levels)


class WitnessStateDB(StateDB):
    """StateDB over a witness: accounts and storage slots materialize on
    first access by walking the partial state trie; `state_root()` writes
    every dirty account back into the partial trie and recomputes the root.
    Touching anything outside the witness raises StatelessError.

    Write-backs are MEMOIZED (`_applied_*`): what was already written
    into the partial tries is remembered, so a repeated `state_root()`
    call with nothing changed in between re-applies nothing and hashes
    zero nodes (the post-root memo) — the pre-r11 behavior rebuilt
    `changed` from scratch and re-put every changed slot per call.

    `node_db` hands in the witness's digest -> node map decoded earlier
    on the request path (witness_node_db) so each witness is decoded
    exactly once; None decodes here (offline/test callers).

    `scheme` selects the commitment scheme (phant_tpu/commitment/) the
    witness commits state under — the partial tries, the node codec and
    the post-root hash-plan lowering all resolve through it. None means
    the process-wide active scheme (PHANT_COMMITMENT / `--commitment`,
    default the hexary `mpt` scheme, byte-identical to the pre-plugin
    path)."""

    def __init__(
        self,
        state_root: bytes,
        nodes: List[bytes],
        codes: List[bytes],
        node_db: Optional[Dict[bytes, bytes]] = None,
        scheme=None,
    ):
        super().__init__()
        if scheme is None:
            from phant_tpu.commitment import active_scheme

            scheme = active_scheme()
        from phant_tpu.utils.trace import metrics

        self._scheme = scheme
        metrics.count("commitment.state_views", scheme=scheme.name)
        self._db = node_db if node_db is not None else witness_node_db(nodes)
        self._codes = {keccak256(c): c for c in codes}
        self._trie = scheme.partial_trie(state_root, self._db)
        self._seen: set = set()
        self._storage_roots: Dict[bytes, bytes] = {}
        self._storage_ptries: Dict[bytes, PartialTrie] = {}
        self._slots_seen: Dict[bytes, set] = {}  # addr -> slots
        # materialized pre-values, for write-back dirtiness checks: only
        # slots/accounts that actually changed touch the trie at root time
        self._pre_slots: Dict[Tuple[bytes, int], int] = {}
        self._pre_accounts: Dict[bytes, Tuple[int, int, bytes]] = {}
        # the materialized Account object per address: identity change means
        # delete+recreate within the block (journal rollback restores the
        # original object, so identity is a reliable generation marker) —
        # a recreated account starts from an EMPTY storage trie
        self._mat_objs: Dict[bytes, object] = {}
        # post-root write-back memoization (PR 11): what has ALREADY been
        # applied to the partial tries, so repeated state_root() calls
        # are idempotent-cheap and the batched plan path shares one
        # dirtiness scan with the host walk
        self._applied_slots: Dict[Tuple[bytes, int], int] = {}
        self._applied_accounts: Dict[bytes, object] = {}  # tuple | _DELETED
        self._applied_gen: Dict[bytes, object] = {}  # acct identity applied
        self._storage_root_memo: Dict[bytes, bytes] = {}
        self._sroot_dirty: set = set()  # applied writes, root not yet known
        self._post_root_memo: Optional[bytes] = None

    # --- materialization ---------------------------------------------------

    def _materialize(self, addr: bytes) -> None:
        if addr in self._seen:
            return
        self._seen.add(addr)
        leaf = self._trie.get(keccak256(addr))
        if leaf is None:
            return  # witnessed absence
        fields = rlp.decode(leaf)
        if not isinstance(fields, list) or len(fields) != 4:
            raise StatelessError("malformed account leaf in witness")
        nonce = rlp.decode_uint(bytes(fields[0]))
        balance = rlp.decode_uint(bytes(fields[1]))
        storage_root = bytes(fields[2])
        code_hash = bytes(fields[3])
        if code_hash == EMPTY_CODE_HASH:
            code = b""
        else:
            code = self._codes.get(code_hash)
            if code is None:
                raise StatelessError(
                    f"witness is missing code {code_hash.hex()}"
                )
        # pre-state materialization is not journaled: a block rollback must
        # not forget what the witness proved
        acct = Account(nonce=nonce, balance=balance, code=code)
        self.accounts[addr] = acct
        self._storage_roots[addr] = storage_root
        self._pre_accounts[addr] = (nonce, balance, code_hash)
        self._mat_objs[addr] = acct

    def _materialize_slot(self, addr: bytes, slot: int) -> None:
        key = (addr, slot)
        seen = self._slots_seen.setdefault(addr, set())
        if slot in seen:
            return
        seen.add(slot)
        self._materialize(addr)
        acct = self.accounts.get(addr)
        if acct is None:
            return
        if self._mat_objs.get(addr) is not acct:
            return  # recreated after deletion: storage starts empty, the
            # witnessed pre-state slot must NOT leak into the new generation
        sroot = self._storage_roots.get(addr, EMPTY_TRIE_ROOT)
        if sroot == EMPTY_TRIE_ROOT:
            return
        strie = self._storage_ptries.get(addr)
        if strie is None:
            strie = self._scheme.partial_trie(sroot, self._db)
            self._storage_ptries[addr] = strie
        raw = strie.get(keccak256(slot.to_bytes(32, "big")))
        if raw is not None:
            value = rlp.decode_uint(bytes(rlp.decode(raw)))
            acct.storage[slot] = value
            self._pre_slots[key] = value

    # --- overridden accessors ---------------------------------------------

    def account_exists(self, addr):
        self._materialize(addr)
        return super().account_exists(addr)

    def get_account(self, addr):
        self._materialize(addr)
        return super().get_account(addr)

    def _get_or_create(self, addr):
        self._materialize(addr)
        return super()._get_or_create(addr)

    def get_balance(self, addr):
        self._materialize(addr)
        return super().get_balance(addr)

    def get_nonce(self, addr):
        self._materialize(addr)
        return super().get_nonce(addr)

    def get_code(self, addr):
        self._materialize(addr)
        return super().get_code(addr)

    def is_empty(self, addr):
        self._materialize(addr)
        return super().is_empty(addr)

    def touch(self, addr):
        # EIP-158 cleanup (destroy_touched_empty) inspects accounts directly;
        # a touched pre-existing empty account must be materialized or its
        # leaf would silently survive deletion
        self._materialize(addr)
        super().touch(addr)

    def get_storage(self, addr, slot):
        self._materialize_slot(addr, slot)
        return super().get_storage(addr, slot)

    def set_storage(self, addr, slot, value):
        self._materialize_slot(addr, slot)
        return super().set_storage(addr, slot, value)

    # --- post root ----------------------------------------------------------

    def state_root(self) -> bytes:
        """Post-state root over the witnessed subtree — the HOST walk, and
        the oracle the batched device path (post_root_plan / ops/
        root_engine.py) is differential-tested against: write every account
        this execution changed back into the partial trie (untouched
        subtrees contribute their witnessed digests; unchanged materialized
        accounts are skipped — dirtiness check), recomputing storage roots
        for accounts whose slots changed. Deleted accounts (EIP-158 cleanup,
        selfdestruct) are removed with full node collapse. Idempotent-cheap:
        a repeated call with nothing changed applies nothing and returns
        the memoized root without hashing a single node."""
        changed_any = False
        for addr in sorted(self._seen | set(self.accounts)):
            acct = self.accounts.get(addr)
            key = keccak256(addr)
            if acct is None:
                if self._delete_account_leaf(addr, key):
                    changed_any = True
                continue
            sroot = self._storage_root_of(addr, acct)
            target = (acct.nonce, acct.balance, sroot, acct.code_hash())
            if target == self._account_baseline(addr, acct):
                continue  # account unchanged: leave its leaf alone
            self._post_root_memo = None
            self._trie.put(key, self._account_leaf_value(*target))
            self._applied_accounts[addr] = target
            changed_any = True
        if not changed_any and self._post_root_memo is not None:
            return self._post_root_memo
        root = self._trie.root_hash()
        self._post_root_memo = root
        return root

    @staticmethod
    def _account_leaf_value(
        nonce: int, balance: int, sroot: bytes, code_hash: bytes
    ) -> bytes:
        from phant_tpu.commitment import account_leaf_value

        return account_leaf_value(nonce, balance, sroot, code_hash)

    def _delete_account_leaf(self, addr: bytes, key: bytes) -> bool:
        """Delete the account's leaf if the trie currently holds one
        (pre-existed, or put by an earlier state_root call); idempotent."""
        applied = self._applied_accounts.get(addr, _UNSET)
        if applied is _DELETED:
            return False
        if applied is _UNSET and addr not in self._pre_accounts:
            return False
        self._post_root_memo = None  # trie mutates: memo invalid NOW (an
        # abort path between here and the recompute must not resurrect it)
        self._trie.delete(key)
        self._applied_accounts[addr] = _DELETED
        return True

    def _account_baseline(self, addr: bytes, acct: Account):
        """What the account trie currently holds for `addr`: the last
        applied leaf fields, or the witnessed pre-state when nothing was
        applied and the materialized identity is unchanged. _UNSET (never
        equal to a target tuple) when the address has no leaf under the
        current account generation — a create/recreate must put."""
        applied = self._applied_accounts.get(addr, _UNSET)
        if applied is not _UNSET:
            return applied
        pre = self._pre_accounts.get(addr)
        if pre is not None and self._mat_objs.get(addr) is acct:
            return (
                pre[0],
                pre[1],
                self._storage_roots.get(addr, EMPTY_TRIE_ROOT),
                pre[2],
            )
        return _UNSET

    def _storage_changes(
        self, addr: bytes, acct: Account
    ) -> Tuple[bytes, Dict[int, int], bool]:
        """(pre_root, {slot: value} still to apply, fresh): the pending
        storage-trie writes for one account, diffed against what earlier
        state_root/post_root_plan calls already applied."""
        fresh = self._mat_objs.get(addr) is not acct  # created (or recreated
        # after selfdestruct) this block: storage starts from the empty trie
        if fresh and self._applied_gen.get(addr) is not acct:
            # a recreated account invalidates writes applied under the
            # dead generation — its storage trie restarts from EMPTY
            for k in [k for k in self._applied_slots if k[0] == addr]:
                del self._applied_slots[k]
            self._storage_ptries.pop(addr, None)
            self._storage_root_memo.pop(addr, None)
            self._sroot_dirty.discard(addr)
            self._applied_gen[addr] = acct
        pre_root = (
            EMPTY_TRIE_ROOT
            if fresh
            else self._storage_roots.get(addr, EMPTY_TRIE_ROOT)
        )
        dirty = set(self._slots_seen.get(addr, ()))
        dirty |= set(acct.storage)
        changed: Dict[int, int] = {}
        for s in dirty:
            cur = acct.storage.get(s, 0)
            k = (addr, s)
            if k in self._applied_slots:
                base = self._applied_slots[k]
            elif fresh:
                base = 0
            else:
                base = self._pre_slots.get(k, 0)
            if cur != base:
                changed[s] = cur
        return pre_root, changed, fresh

    def _apply_storage(
        self, addr: bytes, acct: Account, pre_root: bytes, changed: Dict[int, int]
    ) -> PartialTrie:
        """Write one account's pending slot changes into its storage trie
        (host structural work — identical on the host-walk and plan
        paths); the root itself is computed by the caller's path."""
        strie = self._storage_ptries.get(addr)
        if strie is None:
            strie = self._scheme.partial_trie(pre_root, self._db)
            self._storage_ptries[addr] = strie
        self._post_root_memo = None  # the account leaf WILL change; an
        # abort before the recompute must not leave the old memo live
        for slot in sorted(changed):
            value = changed[slot]
            key = keccak256(slot.to_bytes(32, "big"))
            if value == 0:
                strie.delete(key)  # storage-zeroing: delete with collapse
            else:
                strie.put(key, rlp.encode(rlp.encode_uint(value)))
            self._applied_slots[(addr, slot)] = value
        self._applied_gen[addr] = acct
        self._storage_root_memo.pop(addr, None)
        self._sroot_dirty.add(addr)
        return strie

    def _storage_root_of(self, addr: bytes, acct: Account) -> bytes:
        pre_root, changed, _fresh = self._storage_changes(addr, acct)
        if changed:
            self._apply_storage(addr, acct, pre_root, changed)
        if addr in self._sroot_dirty:
            root = self._storage_ptries[addr].root_hash()
            self._storage_root_memo[addr] = root
            self._sroot_dirty.discard(addr)
            return root
        return self._storage_root_memo.get(addr, pre_root)

    # --- batched post root (the serving device path) -------------------------

    def post_root_plan(self) -> Optional[PostRootPlan]:
        """Fused account+storage HashPlan for the BATCHED post-root path
        (ops/root_engine.py): trie mutations are applied on the host
        exactly like state_root() (structure is host work either way),
        but every keccak is left to the plan — HashNode digests enter
        parent templates as constants, dirty nodes become per-level RLP
        templates with 32-byte child holes, and each dirty storage
        trie's root is a hole INSIDE its account leaf, so ONE plan per
        request re-derives every digest up to the post root.

        Returns None when the host walk should run instead: nothing is
        dirty (the memo answers), or the ACCOUNT trie contains embedded
        (<32 B) nodes. A storage trie with embedded nodes falls back
        ALONE — its root is hashed on the host and baked into the leaf
        as a constant, the same per-trie fallback trie_root_device
        applies. Either way the tries are left consistent: a follow-up
        state_root() is always correct (and cheap, via the memos)."""
        builder = self._scheme.plan_builder()
        patches: List[_RootPatch] = []
        changed_any = False
        for addr in sorted(self._seen | set(self.accounts)):
            acct = self.accounts.get(addr)
            key = keccak256(addr)
            if acct is None:
                if self._delete_account_leaf(addr, key):
                    changed_any = True
                continue
            pre_root, changed, _fresh = self._storage_changes(addr, acct)
            hole = None  # (gi, level) of a plan-computed storage root
            if changed:
                strie = self._apply_storage(addr, acct, pre_root, changed)
                sroot: Optional[bytes] = None
                if strie.root is None:
                    sroot = EMPTY_TRIE_ROOT
                else:
                    hole = builder.try_subtree(strie.root)
                    if hole is None:
                        # embedded-node storage trie: host fallback for
                        # THIS trie only (constant root in the leaf)
                        sroot = strie.root_hash()
                if sroot is not None:
                    self._storage_root_memo[addr] = sroot
                    self._sroot_dirty.discard(addr)
            elif addr in self._sroot_dirty:
                sroot = self._storage_ptries[addr].root_hash()
                self._storage_root_memo[addr] = sroot
                self._sroot_dirty.discard(addr)
            else:
                sroot = self._storage_root_memo.get(addr, pre_root)
            fields = (acct.nonce, acct.balance, acct.code_hash())
            if hole is None:
                target = (acct.nonce, acct.balance, sroot, acct.code_hash())
                if target == self._account_baseline(addr, acct):
                    continue
                self._post_root_memo = None
                self._trie.put(key, self._account_leaf_value(*target))
                self._applied_accounts[addr] = target
            else:
                prefix, suffix = self._account_leaf_segments(fields)
                self._post_root_memo = None
                self._trie.put(key, prefix + b"\x00" * 32 + suffix)
                leaf = _find_leaf(self._trie, key)
                if leaf is None:  # cannot happen for 32-byte keccak keys
                    self._repair_pending(patches)
                    return None
                builder.value_holes[id(leaf)] = (
                    prefix,
                    suffix,
                    hole[0],
                    hole[1],
                )
                patches.append(
                    _RootPatch(addr, leaf, prefix, suffix, hole[0], fields)
                )
            changed_any = True
        if not changed_any:
            return None  # state_root() answers from the memo / pre root
        root = self._trie.root
        res = builder.try_subtree(root) if root is not None else None
        if res is None:
            self._repair_pending(patches)
            return None
        plan = builder.finish(res[0], [p.gi for p in patches] + [res[0]])
        if plan is None:
            self._repair_pending(patches)
            return None
        self._post_root_memo = None  # stale until apply_post_root
        return PostRootPlan(plan, patches)

    @staticmethod
    def _account_leaf_segments(fields: Tuple[int, int, bytes]) -> Tuple[bytes, bytes]:
        """(prefix, suffix) of the account-leaf RLP value around the
        32-byte storage-root slot, derived structurally (never by byte
        search — code hashes are attacker-influenced content)."""
        nonce, balance, code_hash = fields
        enc_n = rlp.encode(rlp.encode_uint(nonce))
        enc_b = rlp.encode(rlp.encode_uint(balance))
        value0 = WitnessStateDB._account_leaf_value(
            nonce, balance, b"\x00" * 32, code_hash
        )
        payload_len = len(enc_n) + len(enc_b) + 66
        off = (len(value0) - payload_len) + len(enc_n) + len(enc_b) + 1
        return value0[:off], value0[off + 32 :]

    def _repair_pending(self, patches: List[_RootPatch]) -> None:
        """Plan build aborted after placeholder leaves were put: compute
        the pending storage roots on the host and patch the real leaves
        back in, leaving the tries exactly as state_root() would."""
        for p in patches:
            sroot = self._storage_ptries[p.addr].root_hash()
            p.leaf.value = p.prefix + sroot + p.suffix
            self._storage_root_memo[p.addr] = sroot
            self._sroot_dirty.discard(p.addr)
            self._applied_accounts[p.addr] = (
                p.fields[0],
                p.fields[1],
                sroot,
                p.fields[2],
            )
        if patches:
            self._trie._enc_cache.clear()

    def apply_post_root(
        self, prp: PostRootPlan, digests: Sequence[bytes]
    ) -> bytes:
        """Fold a resolved plan's digests back into the host state: patch
        each placeholder account leaf with its plan-computed storage root,
        memoize, and return the post root (the plan's LAST out row). After
        this the host tries are canonical again — a follow-up state_root()
        returns the same root from the memo without hashing."""
        for patch, sroot in zip(prp.patches, digests):
            patch.leaf.value = patch.prefix + sroot + patch.suffix
            self._storage_root_memo[patch.addr] = sroot
            self._sroot_dirty.discard(patch.addr)
            self._applied_accounts[patch.addr] = (
                patch.fields[0],
                patch.fields[1],
                sroot,
                patch.fields[2],
            )
        if prp.patches:
            self._trie._enc_cache.clear()
        root = bytes(digests[-1])
        self._post_root_memo = root
        return root

    def copy(self):  # pragma: no cover — stateless runs are one-shot
        raise StatelessError("WitnessStateDB cannot be copied")


def _find_leaf(trie: PartialTrie, key: bytes) -> Optional[LeafNode]:
    """The LeafNode object holding `key` (secure tries: all keys are
    32-byte digests, so a present key always terminates in a leaf).
    Radix-generic: walks whatever digit alphabet the trie's scheme uses."""
    node, path = trie.root, list(trie._digits(key))
    while node is not None:
        if isinstance(node, LeafNode):
            return node if node.path == tuple(path) else None
        if isinstance(node, ExtensionNode):
            n = len(node.path)
            if tuple(path[:n]) != node.path:
                return None
            node, path = node.child, path[n:]
            continue
        if isinstance(node, BranchNode):
            if not path:
                return None
            node, path = node.children[path[0]], path[1:]
            continue
        return None  # HashNode: the put would have raised already
    return None


def _batched_root_wanted() -> bool:
    """Route post roots through the serving root lane? PHANT_BATCHED_ROOT
    =0 pins the host walk, =1 forces the lane (tests / XLA-CPU proxy);
    auto engages it exactly when the device route exists (tpu backend +
    live device) — on the pure-CPU path the host walk stays untouched and
    nothing jax-adjacent is ever imported. The per-dispatch host-vs-
    device decision stays with ops/root_engine.py (THE offload-gate
    story): this is only the cheap 'could a device ever be involved'
    pre-filter."""
    import os

    env = os.environ.get("PHANT_BATCHED_ROOT", "auto")
    if env in ("0", "off", ""):
        return False
    if env == "1":
        return True
    from phant_tpu.backend import crypto_backend, jax_device_ok

    return crypto_backend() == "tpu" and jax_device_ok()


def _batched_sig_wanted() -> bool:
    """Route sender recovery through the serving sig lane?
    PHANT_BATCHED_SIG=0 pins the in-request fused native batch, =1 forces
    the lane (tests / XLA-CPU proxy); auto engages it exactly when the
    device route exists (tpu backend + live device) — on the pure-CPU
    path the lane would only add scheduler latency around the SAME fused
    native batch the request already runs. The per-dispatch native-vs-
    device decision stays with ops/sig_engine.py (THE offload-gate
    story, the merged PHANT_TPU_MIN_ECRECOVER floor): this is only the
    cheap 'could a device ever be involved' pre-filter."""
    import os

    env = os.environ.get("PHANT_BATCHED_SIG", "auto")
    if env in ("0", "off", ""):
        return False
    if env == "1":
        return True
    from phant_tpu.backend import crypto_backend, jax_device_ok

    return crypto_backend() == "tpu" and jax_device_ok()


import threading as _threading

#: per-chain-id TxSigner memo for the request path: the signer resolves
#: its PHANT_TPU_MIN_ECRECOVER floor ONCE at construction (the r14
#: signer bugfix), so a per-request construction would put the env read
#: right back on the serving hot path. dict get is GIL-atomic; the lock
#: only serializes first construction.
_sig_signers: dict = {}
_sig_signers_lock = _threading.Lock()


def _request_signer(chain_id: int):
    signer = _sig_signers.get(chain_id)
    if signer is None:
        from phant_tpu.signer.signer import TxSigner

        with _sig_signers_lock:
            signer = _sig_signers.setdefault(chain_id, TxSigner(chain_id))
    return signer


def sender_lane_available() -> bool:
    """Cheap 'is the sig lane in play for this thread right now'
    pre-filter: `_batched_sig_wanted()` plus a live installed scheduler
    that accepts sig work. `run_blocks`' window prefetch and the replay
    engine consult this ONCE per import/segment instead of paying a
    dispatch_sender_recovery round-trip per block to find out the lane
    is off."""
    if not _batched_sig_wanted():
        return False
    from phant_tpu.serving import active_scheduler

    sched = active_scheduler()
    return sched is not None and sched.accepts_sig()


def dispatch_sender_recovery(chain_id: int, txs, rows=None):
    """Dispatch one block's sender recovery through the active
    scheduler's sig lane; returns `resolve() -> senders`, or None when
    the lane is not in play (no scheduler, `_batched_sig_wanted()`
    false, empty tx list).

    The request path calls this at DECODE time and joins just before EVM
    execution (`apply_body`'s `senders=` prefetch parameter is the join
    point), so the merged device ecrecover computes while this thread
    verifies the witness and builds the node db. The signature rows —
    host keccak over RLP, `TxSigner.signature_rows` — are built on THIS
    handler thread (embarrassingly parallel across requests); invalid
    signatures ride the placeholder lane and surface as None senders,
    which `apply_body` raises with the exact per-index message the
    inline `get_senders_batch` path raises (attribution parity is
    differential-tested). A scheduler rejection — overload shed,
    deadline, executor death, at dispatch OR join — degrades to the
    fused native batch over the rows ALREADY built (no second
    signing-hash pass) instead of failing the block: sender recovery
    has a correct local fallback, so the lane may only ever help.

    The resolve-side block time is exported as `sched.sig_wait` — the
    part of the recovery that did NOT hide under witness verification
    (the overlap audit, same reading as `sched.prefetch_wait`).

    `rows=` optionally supplies PRE-BUILT signature rows for the same
    txs: the replay engine's prefetch worker builds a whole segment's
    merged rows off the critical path (under `replay.prefetch`) and
    hands them here so the signing-hash pass isn't repeated at dispatch
    time; `run_blocks`' window prefetch passes txs and lets this build
    them (one pass per WINDOW, not per block — the r18 bugfix)."""
    if not txs or not _batched_sig_wanted():
        return None
    from phant_tpu.serving import active_scheduler
    from phant_tpu.serving.scheduler import SchedulerError

    sched = active_scheduler()
    if sched is None or not sched.accepts_sig():
        return None
    import time as _time

    from phant_tpu.utils.trace import metrics

    signer = _request_signer(chain_id)
    if rows is None:
        with metrics.phase("stateless.sig_rows"):
            rows = signer.signature_rows(list(txs))

    def degrade():
        # shed/crashed lane: recover from the rows ALREADY built (no
        # second signing-hash pass) on the fused native batch —
        # force_cpu because a -32052 may mean the device itself died
        return signer.recover_rows_async(rows, force_cpu=True)()

    try:
        inner = sched.sig_async(rows)
    except SchedulerError:
        return degrade  # shed at admission

    def resolve():
        t0 = _time.perf_counter()
        try:
            senders, meta = inner()
        except SchedulerError:
            return degrade()
        finally:
            metrics.observe("sched.sig_wait", _time.perf_counter() - t0)
        if meta is not None:
            from phant_tpu.utils.trace import current_span

            sp = current_span()
            if sp is not None:
                # sig_-prefixed: the open verify_block span already
                # carries the WITNESS batch record under the bare keys
                sp.attrs.update({f"sig_{k}": v for k, v in meta.items()})
        return senders

    return resolve


def compute_post_root(state: WitnessStateDB) -> bytes:
    """The request path's post-state root.

    Serving mode with a device in reach: build the request's fused
    account+storage hash plan on THIS (handler) thread
    (`post_root_plan` — host structural work, parallel across requests)
    and submit it to the active scheduler's root lane, where concurrent
    requests' plans coalesce into ONE device dispatch per level-shape
    bucket (serving/scheduler.py submit_root, ops/root_engine.py). The
    batch record the scheduler attaches folds into the open
    `verify_block` span exactly like the witness path's. Everything
    else — offline callers, pure-CPU serving, un-plannable tries —
    is the host walk (`state_root()`), byte-identical by construction
    and differential-tested."""
    from phant_tpu.serving import active_scheduler

    if _batched_root_wanted():
        sched = active_scheduler()
        if sched is not None and sched.accepts_root():
            import os

            from phant_tpu.utils.trace import metrics

            # lone-request guard (THE offload-gate story, root_engine.py):
            # plan construction itself costs ~a host walk's encoding, so
            # a request with NO root work queued to coalesce with — and a
            # witness payload the link model rejects alone — keeps the
            # host walk WITHOUT building a plan. PHANT_BATCHED_ROOT=1
            # forces the lane (tests/proxy); under concurrency the queue
            # has company and every request plans.
            if os.environ.get("PHANT_BATCHED_ROOT") != "1":
                if sched.root_backlog() == 0:
                    from phant_tpu.backend import device_offload_pays

                    # witness bytes over-estimate the dirty-template
                    # payload, so this only ever errs toward planning
                    est = sum(map(len, state._db.values()))
                    if not device_offload_pays(est):
                        return state.state_root()
            with metrics.phase("stateless.post_root_plan"):
                prp = state.post_root_plan()
            if prp is not None:
                digests, meta = sched.root_traced(prp.plan)
                if meta is not None:
                    from phant_tpu.utils.trace import current_span

                    sp = current_span()
                    if sp is not None:
                        # root_-prefixed, like the sig lane's sig_ keys:
                        # the open verify_block span already carries the
                        # WITNESS batch record under the bare keys, and
                        # un-prefixed root meta used to CLOBBER it
                        # (queue_wait_ms/batch_id/stage/backend) — the
                        # critpath rollup (obs/critpath.py) reads both
                        # families apart by prefix
                        sp.attrs.update(
                            {f"root_{k}": v for k, v in meta.items()}
                        )
                return state.apply_post_root(prp, digests)
    return state.state_root()


# ---------------------------------------------------------------------------
# witness verification entry (the TPU-batched hot loop)
# ---------------------------------------------------------------------------
# (`_threading` is the module-level alias imported above, at the sig-
# signer memo)

_witness_engine = None
_witness_engine_lock = _threading.Lock()


def shared_witness_engine():
    """Process-global memoized witness verifier (ops/witness_engine.py).

    Consecutive blocks' witnesses overlap heavily (only the previous
    block's written paths change), so the Engine API serving path pays
    only for never-seen nodes on each request — the r2 review's "stateless
    serving path doesn't batch" gap, solved by memoization instead of
    request batching. The engine routes its novel-node hashing through the
    selected crypto backend internally (device batches on
    `--crypto_backend=tpu`, native C otherwise)."""
    global _witness_engine
    with _witness_engine_lock:
        if _witness_engine is None:
            import os

            from phant_tpu.ops.witness_engine import WitnessEngine

            _witness_engine = WitnessEngine(
                max_nodes=int(os.environ.get("PHANT_WITNESS_CACHE", 1 << 20)),
                # -1 = adaptive link-aware routing (the engine's cost model);
                # a fixed floor is an explicit operator override
                device_batch_floor=int(
                    os.environ.get("PHANT_TPU_MIN_KECCAK", -1)
                ),
            )
        return _witness_engine


def verify_witness_nodes(state_root: bytes, nodes: List[bytes]) -> bool:
    """Linked witness verification — the nodes must form a connected subtree
    rooted at `state_root` — through the shared memoized engine. Semantics
    are identical to the host BFS (mpt/proof.py verify_witness_linked) and
    the device kernel (ops/witness_jax.witness_verify_fused); all three are
    differential-tested against each other.

    Serving mode: when a continuous-batching scheduler is installed
    (phant_tpu/serving/ — the Engine API server installs one), the check
    routes through it so concurrent handler threads coalesce into ONE
    engine/device dispatch instead of paying a batch-of-1 each — and with
    `pipeline_depth >= 2` (the default) that dispatch is PIPELINED: the
    executor packs batch N+1 while batch N computes on the device and
    batch N-1 resolves (ops/witness_engine.py begin_batch/resolve_batch).
    The batch record the scheduler attaches (batch_id, batch_size,
    bucket_bytes, backend, cache hit/miss, queue_wait_ms, and for
    pipelined batches the stage + pack_ms/resolve_ms split) folds into
    the caller's open span, so the request's `verify_block` trace names
    the shared dispatch that served it AND the pipeline stage timings it
    rode (phant_tpu/obs/). Scheduler rejections (queue full, deadline,
    executor down) propagate as SchedulerError for the server to map to
    JSON-RPC errors. Without a scheduler — offline tools, tests, the
    spec runner by default — the direct shared-engine path is
    unchanged."""
    if state_root == EMPTY_TRIE_ROOT:
        # the empty pre-state needs (and admits) no witness nodes — same
        # contract as the host BFS (mpt/proof.py verify_witness_linked)
        return not nodes
    if not nodes:
        return False
    from phant_tpu.serving import active_scheduler

    sched = active_scheduler()
    if sched is not None and sched.accepts_witness():
        ok, meta = sched.verify_traced(state_root, nodes)
        if meta is not None:
            from phant_tpu.utils.trace import current_span

            sp = current_span()
            if sp is not None:
                sp.attrs.update(meta)
        return ok
    return shared_witness_engine().verify(state_root, nodes)


def execute_stateless(
    chain_id: int,
    parent_header,
    block,
    pre_state_root: bytes,
    nodes: List[bytes],
    codes: List[bytes],
    fork=None,
    fork_factory=None,
    scheme=None,
):
    """Verify the witness, execute the block against it, and verify the post
    state root. Returns the BlockExecutionResult plus the computed post root.
    Raises StatelessError / BlockError on any failure.

    `fork_factory(state) -> Fork` builds the fork AGAINST THE WITNESS-BACKED
    STATE (a PragueFork must write its EIP-2935 history slots into the
    partial trie, where they are part of the post root); a prebuilt `fork`
    instance is accepted for forks that own no state (FrontierFork preloaded
    with authenticated ancestor hashes).

    `scheme` is the commitment scheme the witness and the header's state
    roots commit under (phant_tpu/commitment/); None = the process-wide
    active scheme (`--commitment`). Witness verification itself is
    scheme-blind — the engine checks subtree-connectedness over the
    scheme's own node encodings.

    Observability: the whole run is one `span("verify_block", block=n)` —
    its JSON trace line carries the witness_verify / witness_decode /
    execute / post_root phase split; failures count into
    `stateless.errors{kind=...}`."""
    from phant_tpu.blockchain.chain import Blockchain, BlockError
    from phant_tpu.utils.trace import metrics, span

    with span(
        "verify_block",
        block=block.header.block_number,
        nodes=len(nodes),
        codes=len(codes),
    ) as sp:
        try:
            # sender recovery dispatches FIRST (the sig lane,
            # ops/sig_engine.py): the merged device ecrecover computes
            # while THIS thread verifies the witness and decodes the
            # node db, and joins just before EVM execution below —
            # apply_body's `senders=` prefetch parameter is the join
            # point, so ecrecover latency hides under witness
            # verification + warm-set prefill. None = no lane in play:
            # apply_body runs today's in-request fused batch.
            resolve_senders = dispatch_sender_recovery(
                chain_id, block.transactions
            )
            with metrics.phase("stateless.witness_verify"):
                witness_ok = verify_witness_nodes(pre_state_root, nodes)
            if not witness_ok:
                raise StatelessError(
                    "witness rejected: not a subtree of preStateRoot"
                )
            with metrics.phase("stateless.witness_decode"):
                # ONE decode per request: the digest map is built here by
                # a single batched C keccak and handed through — the
                # counter-pinned contract (a second decode would double
                # stateless.witness_nodes_decoded per payload)
                state = WitnessStateDB(
                    pre_state_root,
                    nodes,
                    codes,
                    node_db=witness_node_db(nodes),
                    scheme=scheme,
                )
                if fork is None and fork_factory is not None:
                    fork = fork_factory(state)
                # verify_state_root=False: the post-root check moves to
                # the dedicated phase below so it can ride the BATCHED
                # root lane (run_block's inline check would pay the
                # serial host walk first and leave nothing dirty for the
                # plan path — pre-PR-11 the root was in fact computed
                # TWICE per request, once here and once below)
                chain = Blockchain(
                    chain_id, state, parent_header, fork=fork, verify_state_root=False
                )
            with metrics.phase("stateless.execute"):
                # join the sig lane: senders recovered while the phases
                # above ran (None entries = invalid signatures, raised
                # by apply_body with the inline path's exact message)
                senders = (
                    resolve_senders() if resolve_senders is not None else None
                )
                result = chain.run_block(block, senders=senders)
            with metrics.phase("stateless.post_root"):
                # batched through the serving root lane when a device is
                # in reach (ops/root_engine.py); host walk otherwise
                post_root = compute_post_root(state)
                if post_root != block.header.state_root:
                    # the exact check (and error contract) run_block's
                    # verify_state_root path would have applied
                    raise BlockError(
                        f"state root mismatch: {post_root.hex()} != "
                        f"{block.header.state_root.hex()}"
                    )
        except Exception as e:
            # by-kind counter (bounded cardinality: exception class names)
            metrics.count("stateless.errors", kind=type(e).__name__)
            # the span closes on the raise: stamp the failure on it so
            # the sinks see it (the timeline tail-sampler keeps every
            # crashed request — the -32052 postmortem must be in-ring)
            sp.attrs["error"] = type(e).__name__
            # and an error record in the flight ring: a postmortem dump
            # carries the failing block + reason, not just a count
            from phant_tpu.obs.flight import flight

            flight.record(
                "error",
                where="stateless.execute_stateless",
                error_kind=type(e).__name__,
                error=str(e)[:240],
                block=block.header.block_number,
            )
            raise
        metrics.count("stateless.blocks_verified")
        return result, post_root
