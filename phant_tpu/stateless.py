"""Stateless block execution from a witness.

The capability behind `engine_executeStatelessPayloadV1`: execute a block
against ONLY a multiproof witness (RLP trie nodes + contract codes), with no
resident world state, and recompute the post-state root over the witnessed
subtree. The reference client has the Engine API method in its supported
list but no implementation (reference: src/main.zig:24-54 lists it,
main.zig:58-70 implements only newPayloadV2) and skips state roots entirely
(reference: src/blockchain/blockchain.zig:83-85); this module is the north
star's actual product path — witness verification is the TPU-batched hot
loop (phant_tpu/ops/witness_jax.py), and execution runs over a lazily
materialized witness-backed StateDB.

Pieces:
- `PartialTrie`: an MPT reconstructed from witness nodes where unwitnessed
  subtrees are opaque `HashNode`s contributing their digest directly. Reads
  and writes that stay inside the witnessed region work; touching an
  unwitnessed subtree raises StatelessError (the witness is insufficient).
- `WitnessStateDB`: a StateDB that materializes accounts/storage on first
  access by walking the partial trie (account key = keccak(address), slot
  key = keccak(slot_be32)), and whose `state_root()` recomputes the post
  root by writing every dirty account back into the partial trie.

Deletion is fully supported: EIP-158 cleanup of touched-empty accounts,
selfdestruct, and storage-zeroing delete keys from the partial trie with
full branch-collapse/extension-merge re-normalization (phant_tpu/mpt/mpt.py
_delete). The one witness-shaped limit is inherent to stateless execution:
collapsing a branch down to a single unwitnessed (HashNode) sibling needs
that sibling's encoding, so such a witness raises StatelessError — witness
formats must include deletion siblings, as real stateless protocols do.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from phant_tpu import rlp
from phant_tpu.crypto.keccak import keccak256
from phant_tpu.mpt.mpt import (
    BranchNode,
    EMPTY_TRIE_ROOT,
    ExtensionNode,
    LeafNode,
    Trie,
    bytes_to_nibbles,
    decode_hex_prefix,
)
from phant_tpu.state.statedb import StateDB
from phant_tpu.types.account import Account, EMPTY_CODE_HASH


class StatelessError(ValueError):
    """The witness is insufficient or unsupported for this execution."""


@dataclass
class HashNode:
    """An unwitnessed subtree: only its digest is known."""

    digest: bytes


def _decode_node(item: rlp.RLPItem, db: Dict[bytes, bytes]):
    """Decoded witness structure -> node graph (HashNode at witness edges)."""
    if not isinstance(item, list):
        raise StatelessError("trie node is not an RLP list")
    if len(item) == 17:
        branch = BranchNode()
        for i in range(16):
            child = item[i]
            if isinstance(child, list):
                branch.children[i] = _decode_node(child, db)
            elif len(child) == 0:
                branch.children[i] = None
            elif len(child) == 32:
                branch.children[i] = _resolve(bytes(child), db)
            else:
                raise StatelessError("bad branch child reference")
        value = bytes(item[16])
        branch.value = value if value else None
        return branch
    if len(item) == 2:
        path, is_leaf = decode_hex_prefix(bytes(item[0]))
        if is_leaf:
            return LeafNode(path, bytes(item[1]))
        child = item[1]
        if isinstance(child, list):
            return ExtensionNode(path, _decode_node(child, db))
        if len(child) == 32:
            return ExtensionNode(path, _resolve(bytes(child), db))
        raise StatelessError("bad extension child reference")
    raise StatelessError(f"trie node with {len(item)} items")


def _resolve(digest: bytes, db: Dict[bytes, bytes]):
    enc = db.get(digest)
    if enc is None:
        return HashNode(digest)
    return _decode_node(rlp.decode(enc), db)


class PartialTrie(Trie):
    """A trie over witness nodes; unwitnessed subtrees are HashNodes.

    root_hash() stays on the host: a witness subtree is a few hundred nodes,
    below the device-dispatch break-even (see trie_root_hash threshold)."""

    def __init__(self, root_digest: bytes, db: Dict[bytes, bytes]):
        super().__init__()
        if root_digest != EMPTY_TRIE_ROOT:
            node = _resolve(root_digest, db)
            if isinstance(node, HashNode):
                raise StatelessError("witness is missing the root node")
            self.root = node
            self.approx_size = len(db)

    # --- reads ------------------------------------------------------------

    def get(self, key: bytes) -> Optional[bytes]:
        node, path = self.root, bytes_to_nibbles(key)
        while node is not None:
            if isinstance(node, HashNode):
                raise StatelessError(
                    f"witness does not cover key {key.hex()}"
                )
            if isinstance(node, LeafNode):
                return node.value if node.path == tuple(path) else None
            if isinstance(node, ExtensionNode):
                n = len(node.path)
                if tuple(path[:n]) != node.path:
                    return None
                node, path = node.child, path[n:]
                continue
            if not path:
                return node.value
            node, path = node.children[path[0]], path[1:]
        return None

    # --- writes -----------------------------------------------------------

    def put(self, key: bytes, value: bytes) -> None:
        if not value:  # empty value = delete (geth trie semantics)
            self.delete(key)
            return
        self._enc_cache.clear()
        self.root = _insert_partial(self.root, bytes_to_nibbles(key), value)

    def delete(self, key: bytes) -> None:
        """Remove `key` with full node collapse. Raises StatelessError when
        the collapse needs the structure of an unwitnessed sibling (a branch
        left with one HashNode child must merge the nibble into it, which
        requires its encoding) — the witness is insufficient, exactly the
        case stateless witness formats require sibling nodes for."""
        from phant_tpu.mpt.mpt import _delete, _Unresolved

        self._enc_cache.clear()
        try:
            self.root = _delete(self.root, bytes_to_nibbles(key))
        except _Unresolved:
            # _delete mutates in place on the way down, so the trie is now
            # half-deleted (key gone, collapse pending) — poison it so no
            # caller can hash the non-canonical structure
            self._broken = True
            raise StatelessError(
                "deletion collapse crosses an unwitnessed subtree "
                f"(key {key.hex()}); the witness must include sibling nodes"
            ) from None

    # --- hashing ----------------------------------------------------------

    def _ref(self, node):
        if isinstance(node, HashNode):
            return node.digest
        return super()._ref(node)

    def node_encoding(self, node):
        if isinstance(node, HashNode):
            raise StatelessError("cannot encode an unwitnessed subtree")
        return super().node_encoding(node)

    _broken = False  # set by a failed delete(); the structure is no longer
    # canonical and must never be hashed

    def root_hash(self) -> bytes:
        if self._broken:
            raise StatelessError(
                "partial trie is poisoned by a failed deletion collapse"
            )
        if isinstance(self.root, HashNode):
            return self.root.digest
        return super().root_hash()


def _insert_partial(node, path, value: bytes):
    """mpt._insert with HashNode awareness: descending INTO an unwitnessed
    subtree is an error; splitting an edge NEXT TO one is fine (the HashNode
    keeps contributing its digest from its new position)."""
    from phant_tpu.mpt.mpt import _common_prefix_len

    if node is None:
        return LeafNode(tuple(path), value)
    if isinstance(node, HashNode):
        raise StatelessError("write path crosses an unwitnessed subtree")

    if isinstance(node, LeafNode):
        if node.path == tuple(path):
            node.value = value
            return node
        common = _common_prefix_len(node.path, path)
        branch = BranchNode()
        old_rest, new_rest = node.path[common:], tuple(path[common:])
        if not old_rest:
            branch.value = node.value
        else:
            branch.children[old_rest[0]] = LeafNode(old_rest[1:], node.value)
        if not new_rest:
            branch.value = value
        else:
            branch.children[new_rest[0]] = LeafNode(new_rest[1:], value)
        if common:
            return ExtensionNode(tuple(path[:common]), branch)
        return branch

    if isinstance(node, ExtensionNode):
        common = _common_prefix_len(node.path, path)
        if common == len(node.path):
            node.child = _insert_partial(node.child, path[common:], value)
            return node
        branch = BranchNode()
        ext_rest = node.path[common:]
        if len(ext_rest) == 1:
            branch.children[ext_rest[0]] = node.child
        else:
            branch.children[ext_rest[0]] = ExtensionNode(ext_rest[1:], node.child)
        new_rest = tuple(path[common:])
        if not new_rest:
            branch.value = value
        else:
            branch.children[new_rest[0]] = LeafNode(new_rest[1:], value)
        if common:
            return ExtensionNode(tuple(path[:common]), branch)
        return branch

    # BranchNode
    if not path:
        node.value = value
        return node
    node.children[path[0]] = _insert_partial(node.children[path[0]], path[1:], value)
    return node


# ---------------------------------------------------------------------------
# witness-backed state
# ---------------------------------------------------------------------------


def witness_node_db(nodes: List[bytes]) -> Dict[bytes, bytes]:
    """The digest -> node-bytes map of one witness, built with ONE batched
    C keccak call instead of a per-node scalar loop — the request path's
    one and only witness decode (`stateless.witness_nodes_decoded` counts
    it, so a reintroduced second decode shows up as a doubled counter in
    the phase metrics). The witness-VERIFICATION decode lives elsewhere
    and is amortized: the serving prefetch stage pre-scans batches
    against the engine's intern tables (ops/witness_engine.py
    prefetch_batch), where the steady-state marginal cost per block is
    ~zero (cross-block reuse, PAPERS.md 2408.14217)."""
    from phant_tpu.crypto.keccak import keccak256_batch_cpu
    from phant_tpu.utils.trace import metrics

    metrics.count("stateless.witness_nodes_decoded", len(nodes))
    return dict(zip(keccak256_batch_cpu(nodes), nodes))


class WitnessStateDB(StateDB):
    """StateDB over a witness: accounts and storage slots materialize on
    first access by walking the partial state trie; `state_root()` writes
    every dirty account back into the partial trie and recomputes the root.
    Touching anything outside the witness raises StatelessError.

    `node_db` hands in the witness's digest -> node map decoded earlier
    on the request path (witness_node_db) so each witness is decoded
    exactly once; None decodes here (offline/test callers)."""

    def __init__(
        self,
        state_root: bytes,
        nodes: List[bytes],
        codes: List[bytes],
        node_db: Optional[Dict[bytes, bytes]] = None,
    ):
        super().__init__()
        self._db = node_db if node_db is not None else witness_node_db(nodes)
        self._codes = {keccak256(c): c for c in codes}
        self._trie = PartialTrie(state_root, self._db)
        self._seen: set = set()
        self._storage_roots: Dict[bytes, bytes] = {}
        self._storage_ptries: Dict[bytes, PartialTrie] = {}
        self._slots_seen: Dict[bytes, set] = {}  # addr -> slots
        # materialized pre-values, for write-back dirtiness checks: only
        # slots/accounts that actually changed touch the trie at root time
        self._pre_slots: Dict[Tuple[bytes, int], int] = {}
        self._pre_accounts: Dict[bytes, Tuple[int, int, bytes]] = {}
        # the materialized Account object per address: identity change means
        # delete+recreate within the block (journal rollback restores the
        # original object, so identity is a reliable generation marker) —
        # a recreated account starts from an EMPTY storage trie
        self._mat_objs: Dict[bytes, object] = {}

    # --- materialization ---------------------------------------------------

    def _materialize(self, addr: bytes) -> None:
        if addr in self._seen:
            return
        self._seen.add(addr)
        leaf = self._trie.get(keccak256(addr))
        if leaf is None:
            return  # witnessed absence
        fields = rlp.decode(leaf)
        if not isinstance(fields, list) or len(fields) != 4:
            raise StatelessError("malformed account leaf in witness")
        nonce = rlp.decode_uint(bytes(fields[0]))
        balance = rlp.decode_uint(bytes(fields[1]))
        storage_root = bytes(fields[2])
        code_hash = bytes(fields[3])
        if code_hash == EMPTY_CODE_HASH:
            code = b""
        else:
            code = self._codes.get(code_hash)
            if code is None:
                raise StatelessError(
                    f"witness is missing code {code_hash.hex()}"
                )
        # pre-state materialization is not journaled: a block rollback must
        # not forget what the witness proved
        acct = Account(nonce=nonce, balance=balance, code=code)
        self.accounts[addr] = acct
        self._storage_roots[addr] = storage_root
        self._pre_accounts[addr] = (nonce, balance, code_hash)
        self._mat_objs[addr] = acct

    def _materialize_slot(self, addr: bytes, slot: int) -> None:
        key = (addr, slot)
        seen = self._slots_seen.setdefault(addr, set())
        if slot in seen:
            return
        seen.add(slot)
        self._materialize(addr)
        acct = self.accounts.get(addr)
        if acct is None:
            return
        if self._mat_objs.get(addr) is not acct:
            return  # recreated after deletion: storage starts empty, the
            # witnessed pre-state slot must NOT leak into the new generation
        sroot = self._storage_roots.get(addr, EMPTY_TRIE_ROOT)
        if sroot == EMPTY_TRIE_ROOT:
            return
        strie = self._storage_ptries.get(addr)
        if strie is None:
            strie = PartialTrie(sroot, self._db)
            self._storage_ptries[addr] = strie
        raw = strie.get(keccak256(slot.to_bytes(32, "big")))
        if raw is not None:
            value = rlp.decode_uint(bytes(rlp.decode(raw)))
            acct.storage[slot] = value
            self._pre_slots[key] = value

    # --- overridden accessors ---------------------------------------------

    def account_exists(self, addr):
        self._materialize(addr)
        return super().account_exists(addr)

    def get_account(self, addr):
        self._materialize(addr)
        return super().get_account(addr)

    def _get_or_create(self, addr):
        self._materialize(addr)
        return super()._get_or_create(addr)

    def get_balance(self, addr):
        self._materialize(addr)
        return super().get_balance(addr)

    def get_nonce(self, addr):
        self._materialize(addr)
        return super().get_nonce(addr)

    def get_code(self, addr):
        self._materialize(addr)
        return super().get_code(addr)

    def is_empty(self, addr):
        self._materialize(addr)
        return super().is_empty(addr)

    def touch(self, addr):
        # EIP-158 cleanup (destroy_touched_empty) inspects accounts directly;
        # a touched pre-existing empty account must be materialized or its
        # leaf would silently survive deletion
        self._materialize(addr)
        super().touch(addr)

    def get_storage(self, addr, slot):
        self._materialize_slot(addr, slot)
        return super().get_storage(addr, slot)

    def set_storage(self, addr, slot, value):
        self._materialize_slot(addr, slot)
        return super().set_storage(addr, slot, value)

    # --- post root ----------------------------------------------------------

    def state_root(self) -> bytes:
        """Post-state root over the witnessed subtree: write every account
        this execution changed back into the partial trie (untouched
        subtrees contribute their witnessed digests; unchanged materialized
        accounts are skipped — dirtiness check), recomputing storage roots
        for accounts whose slots changed. Deleted accounts (EIP-158 cleanup,
        selfdestruct) are removed with full node collapse."""
        for addr in sorted(self._seen | set(self.accounts)):
            acct = self.accounts.get(addr)
            key = keccak256(addr)
            if acct is None:
                if addr in self._pre_accounts:  # existed pre-state: delete
                    self._trie.delete(key)
                continue
            sroot = self._storage_root_of(addr, acct)
            pre = self._pre_accounts.get(addr)
            if (
                pre is not None
                and self._mat_objs.get(addr) is acct
                and pre == (acct.nonce, acct.balance, acct.code_hash())
                and sroot == self._storage_roots.get(addr, EMPTY_TRIE_ROOT)
            ):
                continue  # account unchanged: leave its witnessed leaf alone
            leaf = rlp.encode(
                [
                    rlp.encode_uint(acct.nonce),
                    rlp.encode_uint(acct.balance),
                    sroot,
                    acct.code_hash(),
                ]
            )
            self._trie.put(key, leaf)
        return self._trie.root_hash()

    def _storage_root_of(self, addr: bytes, acct: Account) -> bytes:
        fresh = self._mat_objs.get(addr) is not acct  # created (or recreated
        # after selfdestruct) this block: storage starts from the empty trie
        pre_root = (
            EMPTY_TRIE_ROOT if fresh else self._storage_roots.get(addr, EMPTY_TRIE_ROOT)
        )
        dirty = set(self._slots_seen.get(addr, ()))
        dirty |= set(acct.storage)
        changed = {
            s for s in dirty
            if acct.storage.get(s, 0)
            != (0 if fresh else self._pre_slots.get((addr, s), 0))
        }
        if not changed:
            return pre_root
        strie = self._storage_ptries.get(addr) if not fresh else None
        if strie is None:
            strie = PartialTrie(pre_root, self._db)
            self._storage_ptries[addr] = strie
        for slot in sorted(changed):
            value = acct.storage.get(slot, 0)
            key = keccak256(slot.to_bytes(32, "big"))
            if value == 0:
                strie.delete(key)  # storage-zeroing: delete with collapse
            else:
                strie.put(key, rlp.encode(rlp.encode_uint(value)))
        return strie.root_hash()

    def copy(self):  # pragma: no cover — stateless runs are one-shot
        raise StatelessError("WitnessStateDB cannot be copied")


# ---------------------------------------------------------------------------
# witness verification entry (the TPU-batched hot loop)
# ---------------------------------------------------------------------------


import threading as _threading

_witness_engine = None
_witness_engine_lock = _threading.Lock()


def shared_witness_engine():
    """Process-global memoized witness verifier (ops/witness_engine.py).

    Consecutive blocks' witnesses overlap heavily (only the previous
    block's written paths change), so the Engine API serving path pays
    only for never-seen nodes on each request — the r2 review's "stateless
    serving path doesn't batch" gap, solved by memoization instead of
    request batching. The engine routes its novel-node hashing through the
    selected crypto backend internally (device batches on
    `--crypto_backend=tpu`, native C otherwise)."""
    global _witness_engine
    with _witness_engine_lock:
        if _witness_engine is None:
            import os

            from phant_tpu.ops.witness_engine import WitnessEngine

            _witness_engine = WitnessEngine(
                max_nodes=int(os.environ.get("PHANT_WITNESS_CACHE", 1 << 20)),
                # -1 = adaptive link-aware routing (the engine's cost model);
                # a fixed floor is an explicit operator override
                device_batch_floor=int(
                    os.environ.get("PHANT_TPU_MIN_KECCAK", -1)
                ),
            )
        return _witness_engine


def verify_witness_nodes(state_root: bytes, nodes: List[bytes]) -> bool:
    """Linked witness verification — the nodes must form a connected subtree
    rooted at `state_root` — through the shared memoized engine. Semantics
    are identical to the host BFS (mpt/proof.py verify_witness_linked) and
    the device kernel (ops/witness_jax.witness_verify_fused); all three are
    differential-tested against each other.

    Serving mode: when a continuous-batching scheduler is installed
    (phant_tpu/serving/ — the Engine API server installs one), the check
    routes through it so concurrent handler threads coalesce into ONE
    engine/device dispatch instead of paying a batch-of-1 each — and with
    `pipeline_depth >= 2` (the default) that dispatch is PIPELINED: the
    executor packs batch N+1 while batch N computes on the device and
    batch N-1 resolves (ops/witness_engine.py begin_batch/resolve_batch).
    The batch record the scheduler attaches (batch_id, batch_size,
    bucket_bytes, backend, cache hit/miss, queue_wait_ms, and for
    pipelined batches the stage + pack_ms/resolve_ms split) folds into
    the caller's open span, so the request's `verify_block` trace names
    the shared dispatch that served it AND the pipeline stage timings it
    rode (phant_tpu/obs/). Scheduler rejections (queue full, deadline,
    executor down) propagate as SchedulerError for the server to map to
    JSON-RPC errors. Without a scheduler — offline tools, tests, the
    spec runner by default — the direct shared-engine path is
    unchanged."""
    if state_root == EMPTY_TRIE_ROOT:
        # the empty pre-state needs (and admits) no witness nodes — same
        # contract as the host BFS (mpt/proof.py verify_witness_linked)
        return not nodes
    if not nodes:
        return False
    from phant_tpu.serving import active_scheduler

    sched = active_scheduler()
    if sched is not None and sched.accepts_witness():
        ok, meta = sched.verify_traced(state_root, nodes)
        if meta is not None:
            from phant_tpu.utils.trace import current_span

            sp = current_span()
            if sp is not None:
                sp.attrs.update(meta)
        return ok
    return shared_witness_engine().verify(state_root, nodes)


def execute_stateless(
    chain_id: int,
    parent_header,
    block,
    pre_state_root: bytes,
    nodes: List[bytes],
    codes: List[bytes],
    fork=None,
    fork_factory=None,
):
    """Verify the witness, execute the block against it, and verify the post
    state root. Returns the BlockExecutionResult plus the computed post root.
    Raises StatelessError / BlockError on any failure.

    `fork_factory(state) -> Fork` builds the fork AGAINST THE WITNESS-BACKED
    STATE (a PragueFork must write its EIP-2935 history slots into the
    partial trie, where they are part of the post root); a prebuilt `fork`
    instance is accepted for forks that own no state (FrontierFork preloaded
    with authenticated ancestor hashes).

    Observability: the whole run is one `span("verify_block", block=n)` —
    its JSON trace line carries the witness_verify / witness_decode /
    execute / post_root phase split; failures count into
    `stateless.errors{kind=...}`."""
    from phant_tpu.blockchain.chain import Blockchain, BlockError
    from phant_tpu.utils.trace import metrics, span

    with span(
        "verify_block",
        block=block.header.block_number,
        nodes=len(nodes),
        codes=len(codes),
    ):
        try:
            with metrics.phase("stateless.witness_verify"):
                witness_ok = verify_witness_nodes(pre_state_root, nodes)
            if not witness_ok:
                raise StatelessError(
                    "witness rejected: not a subtree of preStateRoot"
                )
            with metrics.phase("stateless.witness_decode"):
                # ONE decode per request: the digest map is built here by
                # a single batched C keccak and handed through — the
                # counter-pinned contract (a second decode would double
                # stateless.witness_nodes_decoded per payload)
                state = WitnessStateDB(
                    pre_state_root, nodes, codes, node_db=witness_node_db(nodes)
                )
                if fork is None and fork_factory is not None:
                    fork = fork_factory(state)
                chain = Blockchain(
                    chain_id, state, parent_header, fork=fork, verify_state_root=True
                )
            with metrics.phase("stateless.execute"):
                result = chain.run_block(block)
            with metrics.phase("stateless.post_root"):
                post_root = state.state_root()
        except Exception as e:
            # by-kind counter (bounded cardinality: exception class names)
            metrics.count("stateless.errors", kind=type(e).__name__)
            # and an error record in the flight ring: a postmortem dump
            # carries the failing block + reason, not just a count
            from phant_tpu.obs.flight import flight

            flight.record(
                "error",
                where="stateless.execute_stateless",
                error_kind=type(e).__name__,
                error=str(e)[:240],
                block=block.header.block_number,
            )
            raise
        metrics.count("stateless.blocks_verified")
        return result, post_root
