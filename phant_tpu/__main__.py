"""CLI entry point: `python -m phant_tpu`.

Equivalent surface to the reference's main (reference: src/main.zig:78-150):
flag parsing (`--engine_api_port/-p`, `--network_id`, `--chainspec`,
reference: main.zig:78-92), chain-config resolution + fork-table dump
(main.zig:109-118), empty StateDB + zero parent header (main.zig:120-140),
Blockchain construction (main.zig:141) and the Engine API HTTP server
(main.zig:143-149). Adds `--crypto_backend` per the north star.
"""

from __future__ import annotations

import argparse
import logging
import sys
import time

from phant_tpu.backend import set_crypto_backend, set_evm_backend
from phant_tpu.blockchain.chain import Blockchain
from phant_tpu.blockchain.fork import fork_for
from phant_tpu.config import ChainConfig, ChainId
from phant_tpu.engine_api.server import EngineAPIServer
from phant_tpu.state.statedb import StateDB
from phant_tpu.types.block import BlockHeader
from phant_tpu.utils.trace import jax_profile
from phant_tpu.version import RELEASE, revision

log = logging.getLogger("phant_tpu")


def build_parser() -> argparse.ArgumentParser:
    """(reference: PhantArgs, main.zig:78-92)"""
    p = argparse.ArgumentParser(
        prog="phant_tpu", description="TPU-native Ethereum execution client"
    )
    p.add_argument(
        "-p",
        "--engine_api_port",
        type=int,
        default=8551,
        help="Specify the port to listen to for Engine API messages",
    )
    p.add_argument(
        "--network_id",
        type=int,
        default=int(ChainId.Mainnet),
        help="Specify the chain id of the network",
    )
    p.add_argument(
        "--chainspec", type=str, default=None,
        help="Specify a custom chainspec JSON file",
    )
    p.add_argument(
        "--crypto_backend",
        choices=("cpu", "tpu"),
        default="cpu",
        help="Backend for the stateless crypto hot loop (keccak/MPT/ecrecover)",
    )
    p.add_argument(
        "--evm_backend",
        choices=("python", "native"),
        default="native",
        help="EVM bytecode interpreter: native C++ core (evmone-equivalent) "
        "or the pure-Python reference interpreter",
    )
    p.add_argument(
        "--commitment",
        choices=("mpt", "binary"),
        default=None,
        help="Commitment scheme for stateless state verification "
        "(phant_tpu/commitment/): hexary keccak MPT (the default) or "
        "fixed-shape binary Merkle. Applies to every "
        "engine_executeStatelessPayloadV1 this node serves — witnesses "
        "and header state roots must commit under the same scheme. "
        "Default: PHANT_COMMITMENT or mpt",
    )
    # the Engine API is a localhost-trust interface; bind loopback by default
    p.add_argument("--host", type=str, default="127.0.0.1", help="Bind address")
    # observability surface (the Engine API port always serves GET /metrics
    # and /healthz; these flags add a standalone scrape port + device traces)
    p.add_argument(
        "--metrics",
        action="store_true",
        help="Also serve GET /metrics and /healthz on a dedicated port "
        "(--metrics-port), separate from the CL-trust Engine API port",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        default=9465,
        help="Port for the standalone metrics server (with --metrics)",
    )
    p.add_argument(
        "--trace-logdir",
        type=str,
        default=None,
        help="Capture a JAX/XLA device trace of the serving process into "
        "this directory (view with TensorBoard or Perfetto)",
    )
    p.add_argument(
        "--slo-budget-ms",
        type=float,
        default=None,
        help="Wall-clock SLO budget per verify_block request: a request "
        "past it is captured as a full span tree into the /debug/slow "
        "exemplar ring (obs/critpath.py; per-phase overrides via "
        "PHANT_SLO_BUDGET_MS_<PHASE>). 0 disables capture. "
        "Default: PHANT_SLO_BUDGET_MS or 0",
    )
    p.add_argument(
        "--profile-dir",
        type=str,
        default=None,
        help="Directory for on-demand profiler captures "
        "(POST /debug/profile?seconds=T — single-flight, window capped "
        "by PHANT_PROFILE_MAX_S). Default: PHANT_PROFILE_DIR or "
        "build/profile",
    )
    p.add_argument(
        "--timeline-sample-n",
        type=int,
        default=None,
        help="Uniform 1-in-N tail-sampling rate of the timeline recorder "
        "(GET /debug/timeline): SLO violators, crashed requests, and "
        "per-phase p99 exemplars are always kept; 1 keeps everything, "
        "0 keeps only the always-kept tiers. "
        "Default: PHANT_TIMELINE_SAMPLE_N or 16",
    )
    p.add_argument(
        "--timeline-dir",
        type=str,
        default=None,
        help="Spool every timeline export to rotated JSON files under "
        "this directory (newest PHANT_TIMELINE_KEEP kept). "
        "Default: PHANT_TIMELINE_DIR or off",
    )
    p.add_argument(
        "--flight-ring",
        type=int,
        default=None,
        help="Capacity (records) of the /debug/flight postmortem ring, "
        "resolved once at server construction; /healthz echoes all "
        "debug-ring capacities. Default: PHANT_FLIGHT_RING or 2048",
    )
    # continuous-batching scheduler (phant_tpu/serving/): the knobs of the
    # admission-queue -> batch-assembler -> executor pipeline
    p.add_argument(
        "--sched-max-batch",
        type=int,
        default=128,
        help="Max verification requests coalesced into one engine/device "
        "batch (scheduler batch assembler)",
    )
    p.add_argument(
        "--sched-max-wait-ms",
        type=float,
        default=5.0,
        help="Max time an under-full batch waits for more requests; bounds "
        "the latency a lone request pays for batching",
    )
    p.add_argument(
        "--sched-queue-depth",
        type=int,
        default=512,
        help="Admission-queue bound; a full queue rejects with JSON-RPC "
        "-32050 (overload shedding) instead of building latency",
    )
    p.add_argument(
        "--sched-pipeline-depth",
        type=int,
        default=None,
        help="Witness batches in flight between pack and resolve: depth "
        ">= 2 overlaps host packing of batch N+1 with device compute / "
        "digest resolve of batch N; 1 serializes (the pre-pipeline "
        "behavior). Default: PHANT_SCHED_PIPELINE_DEPTH or 2",
    )
    p.add_argument(
        "--sched-prefetch",
        type=int,
        choices=(0, 1),
        default=None,
        help="4th pipeline stage: a prefetch worker runs batch N+1's "
        "witness decode + intern-table novelty pre-scan while batch N "
        "is in dispatch/resolve (on whenever the pipeline depth is >= "
        "2; the pre-scan is advisory — pack's lock-held re-check stays "
        "the authoritative commit). 0 pins the 3-stage pipeline. "
        "Default: PHANT_SCHED_PREFETCH or 1",
    )
    # mesh-sharded dispatch (phant_tpu/serving/mesh_exec.py): one
    # pipelined executor per device, each with a device-pinned engine
    p.add_argument(
        "--sched-mesh",
        type=int,
        default=None,
        metavar="N",
        help="Fan witness dispatch out over N mesh devices: one pipelined "
        "executor per device, each owning a WitnessEngine pinned to that "
        "device, with stable bucket-affinity routing (a witness shape "
        "keeps hitting the same device's intern table) plus least-loaded "
        "spillover. 0 = the single-executor path. "
        "Default: PHANT_SCHED_MESH or 0",
    )
    p.add_argument(
        "--sched-mesh-dispatch",
        choices=("affinity", "megabatch"),
        default=None,
        help="Mesh dispatch mode: 'affinity' routes each assembled batch "
        "to one device; 'megabatch' additionally sends a single-bucket "
        "batch that fills --sched-max-batch through ONE whole-mesh "
        "sharded fused kernel call. Default: PHANT_SCHED_MESH_DISPATCH "
        "or affinity",
    )
    p.add_argument(
        "--sched-megabatch-backlog-k",
        type=int,
        default=None,
        metavar="K",
        help="With --sched-mesh-dispatch megabatch, ALSO fire the "
        "whole-mesh fused dispatch whenever queued same-bucket work "
        "(current batch + still-queued same-bucket jobs) reaches mesh "
        "width x K — fusion engages under sustained overload without "
        "sizing --sched-max-batch. 0 keeps the full-batch-only trigger. "
        "Default: PHANT_SCHED_MEGABATCH_BACKLOG_K or 0",
    )
    p.add_argument(
        "--sched-mesh-spill",
        type=int,
        default=None,
        help="Home-device backlog (batches) at which a bucket's batch "
        "spills to the least-loaded device instead. Default: "
        "PHANT_SCHED_MESH_SPILL or 2",
    )
    # multi-tenant QoS (phant_tpu/serving/qos.py): per-tenant lanes,
    # quotas, weighted fair dequeue, and the adaptive batching wait
    p.add_argument(
        "--sched-tenant-quota",
        type=int,
        default=None,
        help="Max queued witness requests PER TENANT lane (X-Phant-Tenant "
        "header); 0 = only the global queue depth bounds a lane. "
        "Default: PHANT_SCHED_TENANT_QUOTA or 0",
    )
    p.add_argument(
        "--sched-tenant-weights",
        type=str,
        default=None,
        help="Weighted-fair dequeue shares as name:weight,... (e.g. "
        "'cl:4,indexer:1'); unlisted tenants weigh 1. Default: "
        "PHANT_SCHED_TENANT_WEIGHTS",
    )
    p.add_argument(
        "--sched-adaptive-wait",
        type=int,
        choices=(0, 1),
        default=None,
        help="1 = shrink the batch-assembly wait as the queue deepens and "
        "widen it when idle (the inference-serving policy); 0 = static "
        "--sched-max-wait-ms. Default: PHANT_SCHED_ADAPTIVE_WAIT or 1",
    )
    p.add_argument(
        "--sched-min-wait-ms",
        type=float,
        default=None,
        help="Adaptive-wait floor once the queue holds ~one full batch. "
        "Default: PHANT_SCHED_MIN_WAIT_MS or 0.2",
    )
    p.add_argument(
        "--http-timeout-s",
        type=float,
        default=None,
        help="Socket read/write deadline per Engine API connection; a "
        "stalled (slow-loris) client frees its handler thread after this "
        "long. <=0 disables. Default: PHANT_HTTP_TIMEOUT_S or 30",
    )
    return p


def make_genesis_parent_header() -> BlockHeader:
    """The zeroed pre-genesis parent the reference starts from
    (reference: main.zig:122-140)."""
    return BlockHeader(
        gas_limit=0x1C9C380,
        base_fee_per_gas=7,
        withdrawals_root=b"\x00" * 32,
    )


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO, format="%(levelname)s %(name)s: %(message)s")
    args = build_parser().parse_args(argv)

    set_crypto_backend(args.crypto_backend)
    set_evm_backend(args.evm_backend)
    if args.commitment is not None:
        # the flag wins over the env; stateless.py / spec tooling read the
        # active scheme through phant_tpu.commitment.active_scheme()
        import os

        os.environ["PHANT_COMMITMENT"] = args.commitment
    from phant_tpu.commitment import active_scheme

    log.info("commitment scheme: %s", active_scheme().name)

    # chain config resolution (reference: main.zig:109-114)
    if args.chainspec is not None:
        config = ChainConfig.from_chainspec_file(args.chainspec)
    else:
        config = ChainConfig.from_chain_id(args.network_id)

    log.info("phant-tpu %s (%s)", RELEASE, revision())
    log.info("chain: %s (id %d)", config.ChainName, config.chainId)
    print(config.dump())  # (reference: config.dump(), main.zig:118)

    state = StateDB()
    fork = fork_for(config, state, 0, int(time.time()))
    log.info("active fork: %s", type(fork).__name__)
    chain = Blockchain(
        chain_id=config.chainId,
        state=state,
        parent_header=make_genesis_parent_header(),
        fork=fork,
        # stateless serving starts from an untracked state: roots for
        # arbitrary payloads can't be checked without the parent state
        verify_state_root=False,
        config=config,
    )

    from phant_tpu.serving import SchedulerConfig, parse_weights

    sched_kwargs = dict(
        max_batch=args.sched_max_batch,
        max_wait_ms=args.sched_max_wait_ms,
        queue_depth=args.sched_queue_depth,
    )
    if args.sched_pipeline_depth is not None:
        sched_kwargs["pipeline_depth"] = args.sched_pipeline_depth
    if args.sched_prefetch is not None:
        sched_kwargs["prefetch"] = bool(args.sched_prefetch)
    # mesh dispatch: a flag wins over its PHANT_SCHED_MESH* env default
    if args.sched_mesh is not None:
        sched_kwargs["mesh_devices"] = args.sched_mesh
    if args.sched_mesh_dispatch is not None:
        sched_kwargs["mesh_dispatch"] = args.sched_mesh_dispatch
    if args.sched_mesh_spill is not None:
        sched_kwargs["mesh_spill_depth"] = args.sched_mesh_spill
    if args.sched_megabatch_backlog_k is not None:
        sched_kwargs["megabatch_backlog_k"] = args.sched_megabatch_backlog_k
    # QoS knobs: a flag wins over its PHANT_SCHED_* env default
    if args.sched_tenant_quota is not None:
        sched_kwargs["tenant_quota"] = args.sched_tenant_quota
    if args.sched_tenant_weights is not None:
        sched_kwargs["tenant_weights"] = parse_weights(args.sched_tenant_weights)
    if args.sched_adaptive_wait is not None:
        sched_kwargs["adaptive_wait"] = bool(args.sched_adaptive_wait)
    if args.sched_min_wait_ms is not None:
        sched_kwargs["min_wait_ms"] = args.sched_min_wait_ms
    if args.http_timeout_s is not None:
        # the handler reads the env per accepted connection
        import os

        os.environ["PHANT_HTTP_TIMEOUT_S"] = str(args.http_timeout_s)
    obs_flags = (
        ("PHANT_SLO_BUDGET_MS", args.slo_budget_ms),
        ("PHANT_PROFILE_DIR", args.profile_dir),
        ("PHANT_TIMELINE_SAMPLE_N", args.timeline_sample_n),
        ("PHANT_TIMELINE_DIR", args.timeline_dir),
        ("PHANT_FLIGHT_RING", args.flight_ring),
    )
    if any(v is not None for _k, v in obs_flags):
        # observability knobs ride the env (the server re-resolves the
        # memoized obs configs — attribution, timeline, flight ring —
        # ONCE at construction)
        import os

        for key, val in obs_flags:
            if val is not None:
                os.environ[key] = str(val)
    sched_config = SchedulerConfig(**sched_kwargs)
    server = EngineAPIServer(
        chain,
        host=args.host,
        port=args.engine_api_port,
        sched_config=sched_config,
    )
    log.info("Engine API listening on %s:%d", args.host, server.port)
    metrics_server = None
    if args.metrics:
        from phant_tpu.engine_api.server import serve_metrics

        metrics_server = serve_metrics(host=args.host, port=args.metrics_port)
    # SIGTERM (orchestrator stop, driver timeout) leaves a postmortem: dump
    # the obs flight ring to build/flight/, then take the same graceful
    # shutdown path as ^C (drain the scheduler, release the socket)
    import signal

    from phant_tpu.obs import flight

    def _on_sigterm(_signum, _frame):
        flight.dump("sigterm")
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _on_sigterm)
    # SIGINT root cause of the mesh-e2e "shutdown hang" (PR 9): a server
    # launched as a shell background job (`python -m phant_tpu ... &` in a
    # non-interactive shell) inherits SIGINT=SIG_IGN per POSIX, and
    # CPython honors an inherited SIG_IGN by never installing the
    # KeyboardInterrupt handler — so ^C/`kill -INT` is silently ignored
    # FOREVER (faulthandler showed the main thread idle in selector.poll,
    # every scheduler/lane thread parked in its timed wait; nothing was
    # actually wedged). Install the handler explicitly, the same way
    # long-running daemons that still want graceful-stop semantics do.
    def _on_sigint(_signum, _frame):
        # a second ^C mid-drain must not abort shutdown (it lands inside
        # scheduler.shutdown's joins and leaks the socket, rc 130):
        # the first SIGINT starts the drain, later ones are ignored
        signal.signal(signal.SIGINT, signal.SIG_IGN)
        raise KeyboardInterrupt

    signal.signal(signal.SIGINT, _on_sigint)

    try:
        # --trace-logdir wraps the whole serving run in the JAX profiler
        # (no-op without the flag) so TPU kernel dispatches of served
        # payloads land in a TensorBoard/Perfetto trace
        with jax_profile(args.trace_logdir):
            server.serve_forever()
    except KeyboardInterrupt:
        server.shutdown()
        if metrics_server is not None:
            metrics_server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
