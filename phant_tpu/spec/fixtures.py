"""Loader for ethereum/execution-spec-tests blockchain fixtures (JSON).

The fixture format is the correctness oracle, exactly as in the reference
(reference: src/tests/spec_tests.zig:30-132): pre-state, genesis RLP, a list
of blocks (with optional expectException), and a post-state to diff.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Tuple

from phant_tpu.types.account import Account
from phant_tpu.utils.hexutils import hex_to_address, hex_to_bytes, hex_to_int


@dataclass
class FixtureBlock:
    rlp: bytes
    expect_exception: Optional[str] = None


@dataclass
class Fixture:
    name: str
    network: str
    genesis_rlp: bytes
    genesis_header_json: dict
    blocks: List[FixtureBlock]
    last_block_hash: bytes
    pre: Dict[bytes, Account]
    post_state: Dict[bytes, Account]
    seal_engine: str = "NoProof"


def parse_alloc(alloc: dict) -> Dict[bytes, Account]:
    """{address: {nonce, balance, code, storage}} with 0x-hex values
    (reference: src/tests/spec_tests.zig:143-165)."""
    out: Dict[bytes, Account] = {}
    for addr_hex, fields_json in alloc.items():
        storage = {
            hex_to_int(k): hex_to_int(v)
            for k, v in fields_json.get("storage", {}).items()
            if hex_to_int(v) != 0
        }
        out[hex_to_address(addr_hex)] = Account(
            nonce=hex_to_int(fields_json.get("nonce", "0x0")),
            balance=hex_to_int(fields_json.get("balance", "0x0")),
            code=hex_to_bytes(fields_json.get("code", "0x")),
            storage=storage,
        )
    return out


def load_fixture_file(path: Path) -> Iterator[Fixture]:
    data = json.loads(Path(path).read_text())
    for name, fx in data.items():
        if not isinstance(fx, dict) or name.startswith("_"):
            # not a blockchain-test entry (e.g. the mainnet tx golden
            # corpus shares tests/fixtures/: an "_info" dict + a
            # "transactions" list) — other harnesses own those. A dict
            # entry MISSING required keys still fails loudly below;
            # skipping on absent "blocks" would let truncated fixtures
            # silently drop out of the suite.
            continue
        blocks = [
            FixtureBlock(
                rlp=hex_to_bytes(b["rlp"]),
                expect_exception=b.get("expectException"),
            )
            for b in fx["blocks"]
        ]
        yield Fixture(
            name=name,
            network=fx["network"],
            genesis_rlp=hex_to_bytes(fx["genesisRLP"]),
            genesis_header_json=fx["genesisBlockHeader"],
            blocks=blocks,
            last_block_hash=hex_to_bytes(fx["lastblockhash"]),
            pre=parse_alloc(fx["pre"]),
            post_state=parse_alloc(fx.get("postState") or {}),
            seal_engine=fx.get("sealEngine", "NoProof"),
        )


def walk_fixtures(root: Path) -> Iterator[Tuple[Path, Fixture]]:
    """Yield every fixture in every JSON under `root`
    (reference: src/tests/spec_tests.zig:173-183)."""
    for path in sorted(Path(root).rglob("*.json")):
        for fixture in load_fixture_file(path):
            yield path, fixture
