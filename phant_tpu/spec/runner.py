"""Execution-spec-tests fixture runner.

Equivalent surface to the reference's FixtureTest.run
(reference: src/tests/spec_tests.zig:58-132): build pre-state, decode
genesis, run each block through the Blockchain honoring expectException,
then diff the full post-state (nonce / balance / every storage slot).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import List, Optional

from phant_tpu.blockchain.chain import Blockchain, BlockError
from phant_tpu.spec.fixtures import Fixture, walk_fixtures
from phant_tpu.state.statedb import StateDB
from phant_tpu.types.block import Block
from phant_tpu import rlp


class FixtureFailure(AssertionError):
    pass


@dataclass
class RunStats:
    passed: int = 0
    failed: int = 0
    failures: List[str] = field(default_factory=list)


def run_fixture(fixture: Fixture) -> None:
    """Raises FixtureFailure on any divergence from the fixture oracle."""
    # deep-copy the pre-state: execution mutates Account objects in place,
    # and a fixture may be run more than once (e.g. per EVM backend)
    state = StateDB({addr: acct.copy() for addr, acct in fixture.pre.items()})
    genesis = Block.decode(fixture.genesis_rlp)

    chain = Blockchain(
        chain_id=1,  # fixtures run on chain id 1 (SpecTest network)
        state=state,
        parent_header=genesis.header,
    )

    last_valid_hash = genesis.header.hash()
    for i, fb in enumerate(fixture.blocks):
        try:
            block = Block.decode(fb.rlp)
            # run_block journals and rolls back internally: an invalid
            # block leaves no trace (decode failures touch no state)
            chain.run_block(block)
            ran_ok = True
        except (BlockError, rlp.DecodeError, ValueError, KeyError, IndexError) as e:
            ran_ok = False
            error = e
        if fb.expect_exception:
            if ran_ok:
                raise FixtureFailure(
                    f"{fixture.name}: block {i} expected exception "
                    f"{fb.expect_exception!r} but ran fine"
                )
            continue  # invalid block correctly rejected; state untouched? see note
        if not ran_ok:
            raise FixtureFailure(f"{fixture.name}: block {i} failed: {error}")
        last_valid_hash = chain.parent_header.hash()

    if last_valid_hash != fixture.last_block_hash:
        raise FixtureFailure(
            f"{fixture.name}: lastblockhash mismatch "
            f"{last_valid_hash.hex()} != {fixture.last_block_hash.hex()}"
        )

    diff_post_state(fixture, state)


def diff_post_state(fixture: Fixture, state: StateDB) -> None:
    """(reference: spec_tests.zig:103-129)"""
    for addr, want in fixture.post_state.items():
        got = state.get_account(addr)
        if got is None:
            if want.is_empty() and not want.storage:
                continue
            raise FixtureFailure(f"{fixture.name}: missing account 0x{addr.hex()}")
        if got.nonce != want.nonce:
            raise FixtureFailure(
                f"{fixture.name}: 0x{addr.hex()} nonce {got.nonce} != {want.nonce}"
            )
        if got.balance != want.balance:
            raise FixtureFailure(
                f"{fixture.name}: 0x{addr.hex()} balance {got.balance} != {want.balance}"
            )
        if got.code != want.code:
            raise FixtureFailure(f"{fixture.name}: 0x{addr.hex()} code mismatch")
        got_storage = {k: v for k, v in got.storage.items() if v}
        want_storage = {k: v for k, v in want.storage.items() if v}
        if got_storage != want_storage:
            raise FixtureFailure(
                f"{fixture.name}: 0x{addr.hex()} storage {got_storage} != {want_storage}"
            )


def run_directory(root: Path) -> RunStats:
    stats = RunStats()
    for path, fixture in walk_fixtures(root):
        try:
            run_fixture(fixture)
            stats.passed += 1
        except Exception as e:  # noqa: BLE001 — collect everything for the report
            stats.failed += 1
            stats.failures.append(f"{path.name} :: {fixture.name} :: {e}")
    return stats


def main() -> int:
    import argparse

    parser = argparse.ArgumentParser(description="Run execution-spec-tests fixtures")
    parser.add_argument("root", type=Path, help="fixture directory")
    args = parser.parse_args()
    if not args.root.is_dir():
        parser.error(f"fixture directory not found: {args.root}")
    stats = run_directory(args.root)
    if stats.passed + stats.failed == 0:
        parser.error(f"no fixture JSONs under {args.root}")
    for line in stats.failures:
        print("FAIL", line)
    print(f"{stats.passed} passed, {stats.failed} failed")
    return 1 if stats.failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
