"""Execution-spec-tests fixture runner.

Equivalent surface to the reference's FixtureTest.run
(reference: src/tests/spec_tests.zig:58-132): build pre-state, decode
genesis, run each block through the Blockchain honoring expectException,
then diff the full post-state (nonce / balance / every storage slot).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import List, Optional

from phant_tpu.blockchain.chain import Blockchain, BlockError
from phant_tpu.spec.fixtures import Fixture, walk_fixtures
from phant_tpu.state.statedb import StateDB
from phant_tpu.types.block import Block
from phant_tpu import rlp


class FixtureFailure(AssertionError):
    pass


@dataclass
class RunStats:
    passed: int = 0
    failed: int = 0
    failures: List[str] = field(default_factory=list)


def run_fixture(fixture: Fixture) -> None:
    """Raises FixtureFailure on any divergence from the fixture oracle."""
    # deep-copy the pre-state: execution mutates Account objects in place,
    # and a fixture may be run more than once (e.g. per EVM backend)
    state = StateDB({addr: acct.copy() for addr, acct in fixture.pre.items()})
    genesis = Block.decode(fixture.genesis_rlp)

    # fork selection from the fixture's network name (the reference
    # hardcodes a Prague fork instance in its one engine-api test and runs
    # fixtures on Frontier BLOCKHASH semantics, spec_tests.zig:82-100)
    fork = None
    net = fixture.network.lower()
    if "cancun" in net:
        from phant_tpu.blockchain.fork import CancunFork

        fork = CancunFork(state)  # pre-deploys beacon-roots if absent
    elif "prague" in net or "osaka" in net:
        from phant_tpu.blockchain.fork import PragueFork

        fork = PragueFork(state)

    chain = Blockchain(
        chain_id=1,  # fixtures run on chain id 1 (SpecTest network)
        state=state,
        parent_header=genesis.header,
        fork=fork,
    )

    last_valid_hash = genesis.header.hash()
    for i, fb in enumerate(fixture.blocks):
        try:
            block = Block.decode(fb.rlp)
            # run_block journals and rolls back internally: an invalid
            # block leaves no trace (decode failures touch no state)
            chain.run_block(block)
            ran_ok = True
        except (BlockError, rlp.DecodeError, ValueError, KeyError, IndexError) as e:
            ran_ok = False
            error = e
        if fb.expect_exception:
            if ran_ok:
                raise FixtureFailure(
                    f"{fixture.name}: block {i} expected exception "
                    f"{fb.expect_exception!r} but ran fine"
                )
            continue  # invalid block correctly rejected; state untouched? see note
        if not ran_ok:
            raise FixtureFailure(f"{fixture.name}: block {i} failed: {error}")
        last_valid_hash = chain.parent_header.hash()

    if last_valid_hash != fixture.last_block_hash:
        raise FixtureFailure(
            f"{fixture.name}: lastblockhash mismatch "
            f"{last_valid_hash.hex()} != {fixture.last_block_hash.hex()}"
        )

    diff_post_state(fixture, state)


def diff_post_state(fixture: Fixture, state: StateDB) -> None:
    """(reference: spec_tests.zig:103-129)"""
    for addr, want in fixture.post_state.items():
        got = state.get_account(addr)
        if got is None:
            if want.is_empty() and not want.storage:
                continue
            raise FixtureFailure(f"{fixture.name}: missing account 0x{addr.hex()}")
        if got.nonce != want.nonce:
            raise FixtureFailure(
                f"{fixture.name}: 0x{addr.hex()} nonce {got.nonce} != {want.nonce}"
            )
        if got.balance != want.balance:
            raise FixtureFailure(
                f"{fixture.name}: 0x{addr.hex()} balance {got.balance} != {want.balance}"
            )
        if got.code != want.code:
            raise FixtureFailure(f"{fixture.name}: 0x{addr.hex()} code mismatch")
        got_storage = {k: v for k, v in got.storage.items() if v}
        want_storage = {k: v for k, v in want.storage.items() if v}
        if got_storage != want_storage:
            raise FixtureFailure(
                f"{fixture.name}: 0x{addr.hex()} storage {got_storage} != {want_storage}"
            )


def _witness_of_state(accounts, scheme=None) -> tuple:
    """(state_root, nodes, codes): the FULL state trie (accounts + storage
    subtrees) as a witness under `scheme` (default: the hexary MPT —
    byte-identical to the pre-plugin collection). Fixture states are tiny,
    so the complete trie is the simplest provably-sufficient witness — it
    exercises the whole stateless machinery (partial-trie reads/writes,
    deletion collapse, storage-root recompute) with every sibling
    available."""
    from phant_tpu.commitment import get_scheme

    if scheme is None:
        scheme = get_scheme("mpt")
    return scheme.witness_of_state(accounts)


def run_fixture_stateless(fixture: Fixture, scheme=None) -> None:
    """The fixture oracle through `execute_stateless`: every valid block is
    re-executed from ONLY a witness of its pre-state (no resident StateDB)
    and must produce the header's post-state root; every expectException
    block must be rejected statelessly too. A full-state shadow chain rolls
    the canonical state forward between blocks (it is the witness source,
    exactly the role a stateful node plays for a stateless client).

    `scheme` (phant_tpu/commitment/, default the process-wide active
    scheme) selects the commitment scheme: under an alternate scheme the
    fixture is first RE-COMMITTED (commitment/translate.py) so its headers
    carry that scheme's state roots, and the shadow chain's root checks
    run through the scheme instead of the MPT-only StateDB.state_root()."""
    from phant_tpu.commitment import active_scheme
    from phant_tpu.commitment.translate import fork_class_for, translate_fixture
    from phant_tpu.blockchain.fork import FrontierFork
    from phant_tpu.stateless import StatelessError, execute_stateless

    if scheme is None:
        scheme = active_scheme()
    is_mpt = scheme.name == "mpt"
    if not is_mpt:
        fixture = translate_fixture(fixture, scheme)

    # fork-varying system state (EIP-4788 beacon roots, EIP-2935 history)
    # is part of the post root, so the stateless side constructs the SAME
    # fork class over the witness-backed state (fork_factory) that the
    # shadow chain uses over the full state
    fork_cls = fork_class_for(fixture.network)

    state = StateDB({addr: acct.copy() for addr, acct in fixture.pre.items()})
    genesis = Block.decode(fixture.genesis_rlp)
    shadow = Blockchain(
        chain_id=1,
        state=state,
        parent_header=genesis.header,
        fork=fork_cls(state) if fork_cls else None,
        # an alternate scheme's headers carry THAT scheme's roots; the
        # shadow's own MPT root check would reject them — the per-block
        # scheme-root divergence check below replaces it
        verify_state_root=is_mpt,
    )

    past_headers = [genesis.header]
    for i, fb in enumerate(fixture.blocks):
        pre_root, nodes, codes = _witness_of_state(state.accounts, scheme)
        parent = shadow.parent_header
        try:
            block = Block.decode(fb.rlp)
            decode_ok = True
        except (rlp.DecodeError, ValueError, KeyError, IndexError):
            decode_ok = False
        if decode_ok:
            # ONE factory for every fork class, primed with the
            # authenticated ancestor hashes; built AGAINST THE WITNESS
            # STATE when the class binds state (FrontierFork ignores it)
            ancestors = [
                (h.block_number, h.hash()) for h in past_headers[-256:]
            ]

            def fork_factory(st, _anc=ancestors):
                f = fork_cls(st) if fork_cls is not None else FrontierFork()
                for num, hsh in _anc:
                    f.update_parent_block_hash(num, hsh)
                return f

            try:
                _result, post_root = execute_stateless(
                    1,
                    parent,
                    block,
                    pre_root,
                    nodes,
                    codes,
                    fork_factory=fork_factory,
                    scheme=scheme,
                )
                stateless_ok = True
            except (StatelessError, BlockError, ValueError, KeyError, IndexError) as e:
                stateless_ok = False
                stateless_err = e
        else:
            stateless_ok = False
            stateless_err = "block RLP does not decode"

        if fb.expect_exception:
            if stateless_ok:
                raise FixtureFailure(
                    f"{fixture.name}: block {i} expected exception "
                    f"{fb.expect_exception!r} but stateless execution passed"
                )
            continue
        if not stateless_ok:
            raise FixtureFailure(
                f"{fixture.name}: block {i} failed statelessly: {stateless_err}"
            )
        if post_root != block.header.state_root:
            raise FixtureFailure(
                f"{fixture.name}: block {i} stateless post root "
                f"{post_root.hex()} != header {block.header.state_root.hex()}"
            )
        # roll the canonical state forward for the next block's witness
        shadow.run_block(block)
        past_headers.append(block.header)
        # non-mpt: a full scheme-root rebuild per block (storage tries +
        # state trie re-hashed from scratch) — fine at spec-fixture scale,
        # deliberately NOT an incremental scheme trie; pointing the runner
        # at a large corpus under an alternate scheme would want one
        shadow_root = (
            shadow.state.state_root()
            if is_mpt
            else scheme.state_root_of(shadow.state.accounts)
        )
        if shadow_root != post_root:
            raise FixtureFailure(
                f"{fixture.name}: block {i} stateless/full state-root divergence"
            )

    last_valid_hash = shadow.parent_header.hash()
    if last_valid_hash != fixture.last_block_hash:
        raise FixtureFailure(
            f"{fixture.name}: lastblockhash mismatch "
            f"{last_valid_hash.hex()} != {fixture.last_block_hash.hex()}"
        )
    diff_post_state(fixture, state)


def run_directory(root: Path, stateless: bool = False, scheme=None) -> RunStats:
    stats = RunStats()
    for path, fixture in walk_fixtures(root):
        try:
            if stateless:
                run_fixture_stateless(fixture, scheme=scheme)
            else:
                run_fixture(fixture)
            stats.passed += 1
        except Exception as e:  # noqa: BLE001 — collect everything for the report
            stats.failed += 1
            stats.failures.append(f"{path.name} :: {fixture.name} :: {e}")
    return stats


def main() -> int:
    import argparse

    parser = argparse.ArgumentParser(description="Run execution-spec-tests fixtures")
    parser.add_argument("root", type=Path, help="fixture directory")
    parser.add_argument(
        "--stateless",
        action="store_true",
        help="re-execute every block from a witness of its pre-state "
        "(the engine_executeStatelessPayloadV1 machinery)",
    )
    parser.add_argument(
        "--sched",
        action="store_true",
        help="route witness verification through the continuous-batching "
        "scheduler (phant_tpu/serving/) — the IDENTICAL batching code the "
        "Engine API serves with, for serving-path parity runs",
    )
    parser.add_argument(
        "--commitment",
        choices=("mpt", "binary"),
        default=None,
        help="commitment scheme (phant_tpu/commitment/): an alternate "
        "scheme re-commits each fixture's chain (headers re-sealed with "
        "that scheme's state roots) and verifies it through the identical "
        "stateless machinery — the reproducible fixture-translation "
        "differential run (requires --stateless). "
        "Default: PHANT_COMMITMENT or mpt",
    )
    args = parser.parse_args()
    if not args.root.is_dir():
        parser.error(f"fixture directory not found: {args.root}")
    from phant_tpu.commitment import active_scheme, get_scheme

    # the flag wins; a stateless run without it honors the process-wide
    # PHANT_COMMITMENT contract exactly like the serving CLI
    # (__main__.py). The STATEFUL oracle is scheme-irrelevant, so a
    # merely-INHERITED env selection is ignored there — only an explicit
    # contradictory flag errors.
    if args.commitment:
        scheme = get_scheme(args.commitment)
        if scheme.name != "mpt" and not args.stateless:
            parser.error(
                "--commitment only affects stateless runs; add --stateless"
            )
    else:
        scheme = active_scheme() if args.stateless else None
    sched = None
    if args.sched:
        from phant_tpu.serving import VerificationScheduler, install, uninstall

        sched = VerificationScheduler()
        install(sched)
    try:
        stats = run_directory(args.root, stateless=args.stateless, scheme=scheme)
    finally:
        if sched is not None:
            uninstall(sched)
            sched.shutdown()
    if stats.passed + stats.failed == 0:
        parser.error(f"no fixture JSONs under {args.root}")
    for line in stats.failures:
        print("FAIL", line)
    print(f"{stats.passed} passed, {stats.failed} failed")
    return 1 if stats.failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
